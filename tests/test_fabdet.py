"""fabdet unit tests: a firing fixture + negative control per rule
(with the PR-19 sweep's triage re-created in fixture form: unsorted
``json.dump`` of build metadata fires ``unsorted-serialize``, a
wall-clock guard gating a det surface's output path fires
``wallclock-in-det`` — the in-process hash-cache key and the
sorted-listdir MSP walk are the negative controls), the
behavior-pinned fabreg det-hazard migration fixtures run VERBATIM,
loud det.toml parse errors (exit 2 from the CLI), suppression
semantics, CLI plumbing, the toolkit analyzer-registry protocol, the
byte-stability regressions for the sweep's real fixes, and the repo
self-check (the CI gate invariant: ``fabdet fabric_tpu/`` reports 0
unsuppressed findings).

Fixture code lives in *strings* on purpose: only genuine AST shapes
may feed the rules, and the fixtures deliberately read clocks, draw
unseeded randomness and serialize unsorted dicts in ways det-surface
code must never exhibit.  The analyzer itself must run without
jax/numpy/cryptography — pinned here by a subprocess whose import
machinery poisons those modules."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from fabric_tpu.tools import fabdet, fabreg, toolkit
from fabric_tpu.tools.fabdet import (
    DetSpec,
    SurfaceSpec,
    parse_det,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
STORE = "fabric_tpu/store.py"
CHAOS_PATH = "fabric_tpu/tools/fabchaos.py"

#: one fixture table exercising every mode: an outputs surface (a
#: frame writer), a method-qualified outputs surface, a sqlite-row
#: surface with an extra `execute` sink, and the fabchaos det-dict
#: scorecard surface
SPEC = DetSpec(
    surfaces=(
        SurfaceSpec(
            name="frames", module="fabric_tpu/store.py", tier="persisted",
            doc="fixture frame writer", functions=("write_frame",),
        ),
        SurfaceSpec(
            name="blocks", module="fabric_tpu/block.py", tier="persisted",
            doc="fixture method surface", functions=("Store.add_block",),
        ),
        SurfaceSpec(
            name="rows", module="fabric_tpu/db.py", tier="persisted",
            doc="fixture sqlite rows", functions=("DB.commit",),
            sinks=("execute",),
        ),
        SurfaceSpec(
            name="scorecard", module=CHAOS_PATH, tier="replay",
            doc="fixture chaos scorecard", mode="det-dict",
            decorator="scenario",
        ),
    )
)


def det(sources, rules=None, spec=SPEC):
    findings, _stats = fabdet.analyze_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()},
        rules,
        det=spec,
    )
    return findings


def one(src, path=STORE, rules=None, spec=SPEC):
    findings, _n = fabdet.analyze_source(
        textwrap.dedent(src), path, rules, det=spec
    )
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# wallclock-in-det: clock values flowing into a surface
# ---------------------------------------------------------------------------


def test_wallclock_fires_on_time_into_surface():
    findings = one(
        """
        import time

        def write_frame(f):
            stamp = time.time()
            f.write(str(stamp).encode())
        """
    )
    assert rule_ids(findings) == ["wallclock-in-det"]
    assert "frames" in findings[0].message


def test_wallclock_fires_on_datetime_now():
    findings = one(
        """
        import datetime

        def write_frame(f):
            f.write(datetime.datetime.now().isoformat().encode())
        """
    )
    assert rule_ids(findings) == ["wallclock-in-det"]


def test_wallclock_negative_input_derived_bytes_are_clean():
    findings = one(
        """
        def write_frame(f, seq):
            f.write(seq.to_bytes(4, "big"))
        """
    )
    assert findings == []


def test_wallclock_non_surface_function_is_out_of_scope():
    # a diagnostic latency probe in the same module, NOT a declared
    # surface: clocks are fine outside the det contract
    findings = one(
        """
        import time

        def observe_latency():
            return time.perf_counter()
        """,
        path="fabric_tpu/x.py",
    )
    assert findings == []


def test_wallclock_guard_gating_the_output_path_fires():
    # the deliver/server.py cert-expiry shape: the clock never lands in
    # the bytes, but it decides WHETHER the surface emits — a replaying
    # twin with a different clock diverges
    findings = one(
        """
        import time

        def write_frame(f, deadline):
            if time.monotonic() > deadline:
                raise RuntimeError("expired")
            f.write(b"frame")
        """
    )
    assert rule_ids(findings) == ["wallclock-in-det"]


def test_wallclock_interprocedural_same_module_helper():
    findings = one(
        """
        import time

        def _stamp():
            return time.time()

        def write_frame(f):
            f.write(str(_stamp()).encode())
        """
    )
    assert rule_ids(findings) == ["wallclock-in-det"]


def test_wallclock_cross_module_through_an_import():
    findings = det(
        {
            "fabric_tpu/util.py": """
                import time

                def stamp():
                    return time.time()
                """,
            STORE: """
                from fabric_tpu.util import stamp

                def write_frame(f):
                    f.write(str(stamp()).encode())
                """,
        }
    )
    assert rule_ids(findings) == ["wallclock-in-det"]
    assert findings[0].path == STORE


def test_wallclock_method_surface_via_self_helper():
    findings = one(
        """
        import time

        class Store:
            def _now(self):
                return time.time()

            def add_block(self, f, block):
                f.write(block + str(self._now()).encode())
        """,
        path="fabric_tpu/block.py",
    )
    assert rule_ids(findings) == ["wallclock-in-det"]


def test_wallclock_tainted_argument_into_a_surface_call_fires():
    # the router _payload_for shape: the clock value is computed in a
    # NON-surface caller and handed to the surface as an argument
    findings = one(
        """
        import time

        def write_frame(f, stamp):
            f.write(str(stamp).encode())

        def caller(f):
            write_frame(f, time.monotonic())
        """
    )
    assert rule_ids(findings) == ["wallclock-in-det"]


# ---------------------------------------------------------------------------
# unseeded-random-in-det
# ---------------------------------------------------------------------------


def test_random_fires_on_module_level_draw():
    findings = one(
        """
        import random

        def write_frame(f):
            f.write(bytes([random.randrange(256)]))
        """
    )
    assert rule_ids(findings) == ["unseeded-random-in-det"]


def test_random_fires_on_urandom_and_uuid4():
    findings = one(
        """
        import os
        import uuid

        def write_frame(f):
            f.write(os.urandom(8))
            f.write(uuid.uuid4().bytes)
        """
    )
    assert rule_ids(findings) == ["unseeded-random-in-det"] * 2


def test_random_negative_seeded_constructor_is_exempt():
    # the fabreg precedent: random.Random(seed) is the sanctioned
    # seeded discipline the det contract is built on
    findings = one(
        """
        import random

        def write_frame(f, seed):
            rng = random.Random(seed)
            f.write(bytes([rng.randrange(256)]))
        """
    )
    assert findings == []


# ---------------------------------------------------------------------------
# env-in-det
# ---------------------------------------------------------------------------


def test_env_fires_on_pid_into_surface():
    findings = one(
        """
        import os

        def write_frame(f):
            f.write(str(os.getpid()).encode())
        """
    )
    assert rule_ids(findings) == ["env-in-det"]


def test_env_fires_on_environ_read_into_surface():
    findings = one(
        """
        import os

        def write_frame(f):
            f.write(os.environ["HOME"].encode())
        """
    )
    assert rule_ids(findings) == ["env-in-det"]


def test_env_negative_pid_outside_the_surface_is_clean():
    # the registry _save_aot shape: a pid-derived TEMP FILENAME is
    # process-local plumbing; only surface bytes are the contract
    findings = one(
        """
        import os

        def scratch_name(base):
            return f"{base}.{os.getpid()}.tmp"
        """,
        path="fabric_tpu/x.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# hash-order-hazard
# ---------------------------------------------------------------------------


def test_hash_order_fires_on_set_iteration_into_surface():
    findings = one(
        """
        def write_frame(f, keys):
            seen = set(keys)
            for k in seen:
                f.write(k)
        """
    )
    assert rule_ids(findings) == ["hash-order-hazard"]


def test_hash_order_sorted_set_iteration_is_clean():
    findings = one(
        """
        def write_frame(f, keys):
            for k in sorted(set(keys)):
                f.write(k)
        """
    )
    assert findings == []


def test_hash_order_in_process_cache_key_stays_silent():
    # the policy/ast.py:75 shape: hash() feeding an in-process memo
    # dict that never reaches a det surface
    findings = one(
        """
        _cache = {}

        def lookup(source):
            key = hash(source)
            if key not in _cache:
                _cache[key] = len(source)
            return _cache[key]
        """,
        path="fabric_tpu/x.py",
    )
    assert findings == []


def test_hash_order_membership_test_is_order_free():
    # `x in seen` consumes the set without observing its order
    findings = one(
        """
        def write_frame(f, keys, allow):
            ok = set(allow)
            for k in keys:
                if k in ok:
                    f.write(k)
        """
    )
    assert findings == []


# ---------------------------------------------------------------------------
# fs-order-hazard
# ---------------------------------------------------------------------------


def test_fs_order_fires_on_unsorted_listdir_into_surface():
    findings = one(
        """
        import os

        def write_frame(f, d):
            for name in os.listdir(d):
                f.write(name.encode())
        """
    )
    assert rule_ids(findings) == ["fs-order-hazard"]


def test_fs_order_sorted_listdir_is_clean():
    # the msp/configbuilder.py:93 shape — the clean negative control
    findings = one(
        """
        import os

        def write_frame(f, d):
            for name in sorted(os.listdir(d)):
                f.write(name.encode())
        """
    )
    assert findings == []


# ---------------------------------------------------------------------------
# unsorted-serialize
# ---------------------------------------------------------------------------


def test_unsorted_serialize_fires_on_json_dump_anywhere():
    # json.dump writes a file: persisted-by-construction, no [[surface]]
    # row needed (the extbuilder metadata.json shape)
    findings = one(
        """
        import json

        def save(meta, f):
            json.dump(meta, f)
        """,
        path="fabric_tpu/x.py",
    )
    assert rule_ids(findings) == ["unsorted-serialize"]


def test_unsorted_serialize_sort_keys_is_clean():
    findings = one(
        """
        import json

        def save(meta, f):
            json.dump(meta, f, sort_keys=True)
        """,
        path="fabric_tpu/x.py",
    )
    assert findings == []


def test_unsorted_serialize_provably_ordered_value_is_clean():
    findings = one(
        """
        import json

        def save(f, d):
            json.dump(["a", "b", 3], f)
            json.dump(sorted(d.items()), f)
        """,
        path="fabric_tpu/x.py",
    )
    assert findings == []


def test_unsorted_dumps_fires_only_at_a_surface_boundary():
    # json.dumps returns a string: only a hazard once those bytes reach
    # a det surface (the serve OP_STATS shape) — a debug repr is fine
    clean = one(
        """
        import json

        def debug_repr(d):
            return json.dumps(d)
        """,
        path="fabric_tpu/x.py",
    )
    assert clean == []
    findings = one(
        """
        import json

        def write_frame(f, d):
            f.write(json.dumps(d).encode())
        """
    )
    assert rule_ids(findings) == ["unsorted-serialize"]


def test_unsorted_dumps_sorted_at_the_surface_is_clean():
    findings = one(
        """
        import json

        def write_frame(f, d):
            f.write(json.dumps(d, sort_keys=True).encode())
        """
    )
    assert findings == []


# ---------------------------------------------------------------------------
# sqlite-row sinks (the persistent.commit_hash surface shape)
# ---------------------------------------------------------------------------


def test_extra_sink_execute_fires_on_clock_row():
    findings = one(
        """
        import time

        class DB:
            def commit(self, cur, height):
                cur.execute(
                    "insert into savepoints values (?, ?)",
                    (height, time.time()),
                )
        """,
        path="fabric_tpu/db.py",
    )
    assert rule_ids(findings) == ["wallclock-in-det"]


def test_extra_sink_execute_input_derived_rows_are_clean():
    findings = one(
        """
        class DB:
            def commit(self, cur, height, digest):
                cur.execute(
                    "insert into savepoints values (?, ?)",
                    (height, digest),
                )
        """,
        path="fabric_tpu/db.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# det-dict mode: the fabreg det-hazard fixtures, VERBATIM (PR-11 ->
# PR-19 behavior pin; only the expected rule ids are new)
# ---------------------------------------------------------------------------

DET_PREAMBLE = textwrap.dedent(
    """
    import os
    import random
    import time

    def scenario(name):
        def deco(fn):
            return fn
        return deco
    """
)


def test_det_dict_fires_on_wall_clock_in_det():
    findings = det(
        {
            CHAOS_PATH: DET_PREAMBLE + textwrap.dedent("""
                @scenario("s")
                def run_s(seed, clock, scale=1.0):
                    det = {"stamp": time.time()}
                    return det, {}
                """)
        },
    )
    assert rule_ids(findings) == ["wallclock-in-det"]
    assert "run_s" in findings[0].message


def test_det_dict_fires_on_tainted_name_and_unseeded_random():
    findings = det(
        {
            CHAOS_PATH: DET_PREAMBLE + textwrap.dedent("""
                @scenario("s")
                def run_s(seed, clock, scale=1.0):
                    pid = os.getpid()
                    det = {}
                    det["who"] = pid
                    det["roll"] = random.randrange(6)
                    return det, {}
                """)
        },
    )
    assert rule_ids(findings) == ["env-in-det", "unseeded-random-in-det"]


def test_det_dict_taint_respects_source_order_in_nested_blocks():
    # a banned value bound inside a nested block, consumed later at the
    # top level: breadth-first traversal would visit the det write
    # first and miss the taint
    findings = det(
        {
            CHAOS_PATH: DET_PREAMBLE + textwrap.dedent("""
                @scenario("s")
                def run_s(seed, clock, scale=1.0):
                    det = {}
                    if scale > 0:
                        t = time.time()
                    det["elapsed"] = t
                    return det, {}
                """)
        },
    )
    assert rule_ids(findings) == ["wallclock-in-det"]


def test_det_dict_augassign_and_tuple_unpack():
    # det["x"] += <clock> and a, b = time.time(), 1 -> det both count
    findings = det(
        {
            CHAOS_PATH: DET_PREAMBLE + textwrap.dedent("""
                @scenario("s")
                def run_s(seed, clock, scale=1.0):
                    det = {"elapsed": 0.0}
                    det["elapsed"] += time.perf_counter()
                    a, b = time.time(), 1
                    det["t"] = a
                    det["n"] = b
                    return det, {}
                """)
        },
    )
    # the AugAssign and the tainted `a`; `b` is bound to the clean
    # element and stays untainted
    assert rule_ids(findings) == ["wallclock-in-det"] * 2


def test_det_dict_negative_seeded_rng_and_observed_clock():
    findings = det(
        {
            CHAOS_PATH: DET_PREAMBLE + textwrap.dedent("""
                @scenario("s")
                def run_s(seed, clock, scale=1.0):
                    rng = random.Random(seed)
                    t0 = time.perf_counter()
                    det = {"n": rng.randrange(4)}
                    obs = {"elapsed": time.perf_counter() - t0}
                    return det, obs
                """)
        },
    )
    assert findings == []


def test_det_dict_only_applies_to_declared_scorecard_modules():
    findings = det(
        {
            "fabric_tpu/serve/m.py": DET_PREAMBLE + textwrap.dedent("""
                @scenario("s")
                def run_s(seed, clock, scale=1.0):
                    det = {"stamp": time.time()}
                    return det, {}
                """)
        },
    )
    assert findings == []


# ---------------------------------------------------------------------------
# det.toml: the packaged table + loud parse errors
# ---------------------------------------------------------------------------


def test_packaged_det_table_parses_and_names_the_surfaces():
    spec = fabdet.load_default_det()
    names = {s.name for s in spec.surfaces}
    assert {
        "chaos-scorecard", "crash-digest", "snapshot-files",
        "rwset-hashes", "verify-frames", "lane-payload",
        "deliver-stream", "orderer-admission", "block-frames",
        "pvt-frames", "commit-hash", "aot-artifact",
    } <= names
    by_name = {s.name: s for s in spec.surfaces}
    assert by_name["chaos-scorecard"].mode == "det-dict"
    assert by_name["chaos-scorecard"].decorator == "scenario"
    assert by_name["chaos-scorecard"].tier == "replay"
    assert by_name["commit-hash"].sinks == ("execute",)
    assert by_name["commit-hash"].tier == "persisted"
    assert by_name["lane-payload"].tier == "cross-peer"
    for s in spec.surfaces:
        assert s.tier in fabdet.TIERS
        assert s.doc  # every surface names its contract


@pytest.mark.parametrize(
    "text,err",
    [
        ("[[bogus]]\n", "unknown section"),
        ("[sideways]\n", "unknown section"),
        ("name = \"x\"\n", "outside a"),
        ("[[surface]]\nname - \"x\"\n", "expected 'key = value'"),
        ("[[surface]]\nname = maybe\n", "expected"),
        ("[[surface]]\nname = \"x\"\n", "missing required key"),
        (
            "[[surface]]\nname = \"x\"\nmodule = \"m.py\"\n"
            "tier = \"sideways\"\ndoc = \"d\"\nfunctions = [\"f\"]\n",
            "tier must be one of",
        ),
        (
            "[[surface]]\nname = \"x\"\nmodule = \"m.py\"\n"
            "tier = \"replay\"\ndoc = \"d\"\nmode = \"maybe\"\n",
            "mode must be",
        ),
        (
            "[[surface]]\nname = \"x\"\nmodule = \"m.py\"\n"
            "tier = \"replay\"\ndoc = \"d\"\nmode = \"det-dict\"\n",
            "need a 'decorator'",
        ),
        (
            "[[surface]]\nname = \"x\"\nmodule = \"m.py\"\n"
            "tier = \"replay\"\ndoc = \"d\"\n",
            "non-empty 'functions'",
        ),
        (
            "[[surface]]\nname = \"x\"\nmodule = \"m.py\"\n"
            "tier = \"replay\"\ndoc = \"d\"\nfunctions = [\"f\"]\n"
            "[[surface]]\nname = \"x\"\nmodule = \"n.py\"\n"
            "tier = \"replay\"\ndoc = \"d\"\nfunctions = [\"g\"]\n",
            "duplicate surface name",
        ),
    ],
)
def test_det_table_parse_errors_are_loud(text, err):
    with pytest.raises(ValueError, match=err):
        parse_det(text, "<bad>")


def test_cli_rejects_bad_det_table(tmp_path, capsys):
    bad = tmp_path / "det.toml"
    bad.write_text("[[bogus]]\n")
    target = tmp_path / "fabric_tpu" / "m.py"
    target.parent.mkdir()
    target.write_text("x = 1\n")
    rc = fabdet.main(["--det", str(bad), str(target)])
    assert rc == 2
    assert "det table" in capsys.readouterr().err


def test_cli_rejects_missing_det_table(tmp_path, capsys):
    target = tmp_path / "fabric_tpu" / "m.py"
    target.parent.mkdir()
    target.write_text("x = 1\n")
    rc = fabdet.main(["--det", str(tmp_path / "nope.toml"), str(target)])
    assert rc == 2
    assert "det table" in capsys.readouterr().err


def test_declared_surface_missing_from_its_module_is_a_finding():
    # a functions pattern matching nothing = the gate is vacuously
    # passing on that surface: always-on, not maskable via --rules
    spec = DetSpec(
        surfaces=(
            SurfaceSpec(
                name="frames", module=STORE, tier="persisted",
                doc="fixture", functions=("write_frame", "gone_writer"),
            ),
        )
    )
    findings = one(
        """
        def write_frame(f, b):
            f.write(b)
        """,
        rules=["wallclock-in-det"],
        spec=spec,
    )
    assert rule_ids(findings) == ["surface-missing"]
    assert "gone_writer" in findings[0].message


# ---------------------------------------------------------------------------
# suppressions, CLI, syntax errors
# ---------------------------------------------------------------------------


def test_suppression_absorbs_finding_and_is_counted():
    src = textwrap.dedent(
        """
        import time

        def write_frame(f):
            f.write(str(time.time()).encode())  # fabdet: disable=wallclock-in-det  # fixture stamps by design
        """
    )
    findings, n = fabdet.analyze_source(src, STORE, det=SPEC)
    assert findings == []
    assert n == 1


def test_suppression_for_another_rule_does_not_absorb():
    src = textwrap.dedent(
        """
        import time

        def write_frame(f):
            f.write(str(time.time()).encode())  # fabdet: disable=env-in-det  # wrong rule
        """
    )
    findings, n = fabdet.analyze_source(src, STORE, det=SPEC)
    assert rule_ids(findings) == ["wallclock-in-det"]
    assert n == 0


def test_suppression_disable_all_silences_the_line():
    src = textwrap.dedent(
        """
        import time

        def write_frame(f):
            f.write(str(time.time()).encode())  # fabdet: disable=all  # fixture
        """
    )
    findings, n = fabdet.analyze_source(src, STORE, det=SPEC)
    assert findings == []
    assert n == 1


def test_cli_json_and_exit_codes(tmp_path, capsys):
    table = tmp_path / "det.toml"
    table.write_text(
        "[[surface]]\n"
        "name = \"frames\"\n"
        "module = \"fabric_tpu/m.py\"\n"
        "tier = \"persisted\"\n"
        "doc = \"fixture\"\n"
        "functions = [\"write_frame\"]\n"
    )
    bad = tmp_path / "fabric_tpu" / "m.py"
    bad.parent.mkdir()
    bad.write_text(
        "import time\n\n"
        "def write_frame(f):\n"
        "    f.write(str(time.time()).encode())\n"
    )
    rc = fabdet.main(["--json", "--det", str(table), str(bad)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    assert [f["rule"] for f in out["findings"]] == ["wallclock-in-det"]

    clean = tmp_path / "fabric_tpu" / "ok.py"
    clean.write_text("x = 1\n")
    assert fabdet.main(["--det", str(table), str(clean)]) == 0
    capsys.readouterr()

    assert fabdet.main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rid in fabdet.RULES:
        assert rid in listed

    assert fabdet.main(["--rules", "no-such-rule", str(clean)]) == 2
    assert fabdet.main([str(tmp_path / "missing.py")]) == 2
    assert fabdet.main([]) == 2


def test_syntax_error_is_reported_not_raised():
    findings = one("def broken(:\n")
    assert rule_ids(findings) == ["syntax-error"]


def test_analyzer_never_imports_the_analyzed_stack():
    # the gate runs in minimal CI images: fabdet must sweep the whole
    # package with jax/jaxlib/numpy/cryptography UNIMPORTABLE.  A None
    # entry in sys.modules makes any import of the name raise.
    code = textwrap.dedent(
        """
        import sys

        for name in ("jax", "jaxlib", "numpy", "cryptography"):
            sys.modules[name] = None
        from fabric_tpu.tools import fabdet

        rc = fabdet.main(["fabric_tpu/"])
        sys.exit(rc)
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


# ---------------------------------------------------------------------------
# toolkit registry + fabreg staleness protocol + the det-hazard
# retirement pins
# ---------------------------------------------------------------------------


def test_fabdet_is_registered_with_the_toolkit():
    assert "fabdet" in toolkit.ANALYZER_TOOLS
    spec = toolkit.analyzer_spec("fabdet")
    assert spec is not None
    assert spec.module == "fabric_tpu.tools.fabdet"
    # package-scoped: tests craft nondeterminism fixtures by design
    assert spec.pkg_scope_only is True


def test_live_suppression_keys_reports_absorbing_comments():
    # the protocol hook gets no det argument (fabreg calls it blind),
    # so the fixture lives at a packaged-table surface: merkle.py's
    # functions = ["*"] row matches any function
    src = textwrap.dedent(
        """
        import time

        def digest(leaves):
            return str(time.time()).encode()  # fabdet: disable=wallclock-in-det  # fixture stamps by design
        """
    )
    path = "fabric_tpu/ledger/merkle.py"
    keys = fabdet.live_suppression_keys({path: src}, {"wallclock-in-det"})
    assert len(keys) == 1
    ((got_path, line, rule),) = keys
    assert rule == "wallclock-in-det"
    assert got_path.endswith("fabric_tpu/ledger/merkle.py")
    assert line == 5


def test_fabreg_suppression_stale_judges_fabdet_via_the_registry():
    stale = textwrap.dedent(
        """
        def quiet():
            x = 1  # fabdet: disable=wallclock-in-det  # outlived its cause
            return x
        """
    )
    findings, _stats = fabreg.analyze_sources(
        {"fabric_tpu/stale.py": stale},
        rule_ids=["suppression-stale"],
    )
    assert rule_ids(findings) == ["suppression-stale"]
    assert "fabdet" in findings[0].message


def test_fabreg_lost_exactly_the_det_hazard_rule(capsys):
    # the retirement pin: fabreg's rule table is one line shorter and
    # det-hazard is fabdet's whole-program job now
    assert "det-hazard" not in fabreg.RULES
    assert len(fabreg.RULES) == 7
    assert len(fabdet.RULES) == 6
    assert set(fabdet.RULES) == {
        "wallclock-in-det", "unseeded-random-in-det", "env-in-det",
        "hash-order-hazard", "fs-order-hazard", "unsorted-serialize",
    }
    assert fabreg.main(["--list-rules"]) == 0
    listed = [
        ln for ln in capsys.readouterr().out.splitlines() if ln.strip()
    ]
    assert len(listed) == 7
    assert not any("det-hazard" in ln for ln in listed)


# ---------------------------------------------------------------------------
# byte-stability regressions for the PR-19 sweep's real fixes
# ---------------------------------------------------------------------------


def test_extbuilder_metadata_json_bytes_are_key_order_independent(tmp_path):
    # pre-fix, metadata.json followed the package meta's insertion
    # order (type, label, path); sorted dumps make the persisted bytes
    # a pure function of the meta's CONTENT
    from fabric_tpu.chaincode.extbuilder import Launcher
    from fabric_tpu.chaincode.package import PackageStore, package

    raw = package("cc", {"main.py": b"x = 1\n"}, path="src/cc")
    store = PackageStore(str(tmp_path / "pkgs"))
    pkg = store.install(raw)
    launcher = Launcher(str(tmp_path / "run"))
    dirs = launcher._dirs(pkg)
    meta = launcher._materialize(pkg, dirs)
    written = (
        Path(dirs["metadata"]) / "metadata.json"
    ).read_bytes()
    reordered = {k: meta[k] for k in sorted(meta, reverse=True)}
    assert written == json.dumps(reordered, sort_keys=True).encode()


def test_peer_local_sources_bytes_are_approve_order_independent(tmp_path):
    # per-peer lifecycle state: two peers that approved the same
    # bindings in a different order must persist identical bytes
    from fabric_tpu.nodes.peer import PeerNode

    blobs = []
    for order in ((("ch", "zeta"), ("ch", "alpha")),
                  (("ch", "alpha"), ("ch", "zeta"))):
        peer = PeerNode.__new__(PeerNode)
        root = tmp_path / f"peer-{len(blobs)}"
        peer.work_dir = str(root)
        peer._cc_sources = {}
        for channel_id, name in order:
            peer.approve_chaincode(channel_id, name, f"pkg:{name}")
        blobs.append(Path(peer._sources_path()).read_bytes())
    assert blobs[0] == blobs[1]


def test_crashchild_stream_build_is_byte_identical_across_runs(tmp_path):
    # the crash matrix's precondition: same seed -> byte-identical
    # stream dir, INCLUDING meta.json (the sweep's unsorted-dump fix)
    from fabric_tpu.tools import crashchild

    digests = []
    for run in ("a", "b"):
        d = tmp_path / run
        d.mkdir()
        crashchild.build_stream(str(d), seed=7, n_channels=2, n_blocks=3)
        digests.append(
            {p.name: p.read_bytes() for p in sorted(d.iterdir())}
        )
    assert digests[0] == digests[1]
    assert "meta.json" in digests[0]
    meta = json.loads(digests[0]["meta.json"])
    assert meta == {"channels": 2, "blocks": 3}
    assert list(json.loads(digests[0]["meta.json"])) == sorted(meta)


# ---------------------------------------------------------------------------
# repo self-check: the CI gate invariant
# ---------------------------------------------------------------------------


def test_repo_has_zero_unsuppressed_findings():
    findings, stats = fabdet.analyze_paths([str(REPO_ROOT / "fabric_tpu")])
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in findings
    )
    assert stats["files"] > 150
    # the triaged by-design suppressions (NOTES_BUILD PR 19 ledger):
    # the deliver cert/session-expiry gates (2), the orderer
    # identity-expiration admission check (1), the serve wire-deadline
    # budget sites (client 3 + router 3), and the check()-dominated
    # gray-failure scorecard constants (1)
    assert stats["suppressed"] == 10
