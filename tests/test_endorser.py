"""Endorser ProcessProposal + chaincode runtime + tx simulator
(reference core/endorser/endorser.go, core/chaincode, txmgmt/txmgr)."""

import hashlib

import pytest

from conftest import requires_crypto

from fabric_tpu.chaincode import ChaincodeStub, Response, success, error_response
from fabric_tpu.chaincode.support import ChaincodeSupport, TxParams
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx
from fabric_tpu.endorser.endorser import Endorser, ProposalError, unpack_proposal
from fabric_tpu.endorser.txbuilder import create_signed_proposal
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.rwset import KVRead, KVWrite, Version
from fabric_tpu.ledger.simulator import (
    TxSimulator,
    create_composite_key,
    split_composite_key,
)
from fabric_tpu.ledger.statedb import UpdateBatch, VersionedDB
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.protos import peer_pb2, protoutil
from fabric_tpu.validation.msgvalidation import parse_tx_rwset

PROVIDER = SoftwareProvider()


# ---------------- TxSimulator ----------------


def seeded_db():
    db = VersionedDB()
    batch = UpdateBatch()
    batch.put("mycc", "a", b"100", Version(1, 0))
    batch.put("mycc", "b", b"200", Version(1, 1))
    batch.put("mycc", "c", b"300", Version(2, 0))
    db.apply_updates(batch)
    return db


def test_simulator_reads_record_versions():
    sim = TxSimulator(seeded_db())
    assert sim.get_state("mycc", "a") == b"100"
    assert sim.get_state("mycc", "missing") is None
    res = sim.get_tx_simulation_results()
    ns = res.rwset.ns_rw_sets[0]
    assert ns.reads == (
        KVRead("a", Version(1, 0)),
        KVRead("missing", None),
    )


def test_simulator_writes_last_wins_and_no_read_your_writes():
    sim = TxSimulator(seeded_db())
    sim.set_state("mycc", "a", b"1")
    sim.set_state("mycc", "a", b"2")
    # Reference lockbased simulator: reads see committed state only.
    assert sim.get_state("mycc", "a") == b"100"
    sim.delete_state("mycc", "b")
    res = sim.get_tx_simulation_results()
    ns = res.rwset.ns_rw_sets[0]
    assert ns.writes == (
        KVWrite("a", False, b"2"),
        KVWrite("b", True, b""),
    )


def test_simulator_range_query_records_phantom_info():
    sim = TxSimulator(seeded_db())
    results = list(sim.get_state_range_scan_iterator("mycc", "a", "c"))
    assert results == [("a", b"100"), ("b", b"200")]
    res = sim.get_tx_simulation_results()
    rq = res.rwset.ns_rw_sets[0].range_queries[0]
    assert (rq.start_key, rq.end_key, rq.itr_exhausted) == ("a", "c", True)
    assert [r.key for r in rq.raw_reads] == ["a", "b"]


def test_simulator_private_data_hashes():
    sim = TxSimulator(seeded_db())
    sim.set_private_data("mycc", "secret", "k1", b"top")
    res = sim.get_tx_simulation_results()
    coll = res.rwset.ns_rw_sets[0].coll_hashed[0]
    assert coll.collection_name == "secret"
    w = coll.hashed_writes[0]
    assert w.key_hash == hashlib.sha256(b"k1").digest()
    assert w.value_hash == hashlib.sha256(b"top").digest()
    assert res.pvt_writes[("mycc", "secret")][0].value == b"top"
    assert res.pvt_rwset_bytes()  # serializes


def test_simulator_rwset_roundtrips_through_proto():
    sim = TxSimulator(seeded_db())
    sim.get_state("mycc", "a")
    sim.set_state("mycc", "z", b"9")
    res = sim.get_tx_simulation_results()
    assert parse_tx_rwset(res.public_bytes) == res.rwset


def test_composite_keys_roundtrip():
    key = create_composite_key("Color~Name", ["red", "car1"])
    typ, attrs = split_composite_key(key)
    assert (typ, attrs) == ("Color~Name", ["red", "car1"])


# ---------------- chaincode runtime ----------------


class AssetCC:
    """Minimal KV chaincode used across the tests."""

    def init(self, stub):
        return success()

    def invoke(self, stub: ChaincodeStub) -> Response:
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            stub.set_event("put", params[0].encode())
            return success(b"ok")
        if fn == "get":
            v = stub.get_state(params[0])
            return success(v or b"")
        if fn == "putpvt":
            stub.put_private_data("secret", params[0], params[1].encode())
            return success()
        if fn == "call":
            return stub.invoke_chaincode("othercc", [b"get", params[0].encode()])
        if fn == "boom":
            raise RuntimeError("chaincode panic")
        return error_response(f"unknown function {fn}")


class OtherCC:
    def init(self, stub):
        return success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        v = stub.get_state(params[0])
        return success(v or b"")


def make_support():
    support = ChaincodeSupport()
    support.register("mycc", AssetCC())
    support.register("othercc", OtherCC())
    return support


def test_support_execute_and_event():
    support = make_support()
    sim = TxSimulator(seeded_db(), tx_id="tx1")
    resp, event = support.execute(
        TxParams("ch", "tx1", sim), "mycc", [b"put", b"k", b"v"]
    )
    assert resp.status == 200
    assert event.event_name == "put" and event.tx_id == "tx1"
    res = sim.get_tx_simulation_results()
    assert KVWrite("k", False, b"v") in res.rwset.ns_rw_sets[0].writes


def test_support_chaincode_exception_becomes_error_response():
    support = make_support()
    sim = TxSimulator(seeded_db(), tx_id="tx1")
    resp, _ = support.execute(TxParams("ch", "tx1", sim), "mycc", [b"boom"])
    assert resp.status == 500 and "panic" in resp.message


def test_cc2cc_same_channel_shares_rwset():
    support = make_support()
    db = seeded_db()
    batch = UpdateBatch()
    batch.put("othercc", "a", b"other-a", Version(3, 0))
    db.apply_updates(batch)
    sim = TxSimulator(db, tx_id="tx1")
    # the callee reads from ITS OWN namespace (handler.go cc2cc semantics)
    resp, _ = support.execute(TxParams("ch", "tx1", sim), "mycc", [b"call", b"a"])
    assert resp.status == 200 and resp.payload == b"other-a"
    res = sim.get_tx_simulation_results()
    # the callee's read is recorded under its own namespace
    ns_names = [ns.namespace for ns in res.rwset.ns_rw_sets]
    assert "othercc" in ns_names


# ---------------- Endorser.ProcessProposal ----------------


@pytest.fixture(scope="module")
def org():
    return generate_org("org1.example.com", "Org1MSP")


@pytest.fixture
def endorser_net(org, tmp_path):
    msp_mgr = MSPManager([org.msp(provider=PROVIDER)])
    ledger = KVLedger(str(tmp_path / "ledger"), "ch")
    support = make_support()
    peer_signer = SigningIdentity(org.peers[0], PROVIDER)
    endorser = Endorser(
        peer_signer,
        msp_mgr,
        support,
        get_ledger=lambda ch: ledger if ch == "ch" else None,
    )
    client = SigningIdentity(org.users[0], PROVIDER)
    return endorser, client, ledger


@requires_crypto
def test_process_proposal_happy_path(endorser_net):
    endorser, client, _ = endorser_net
    bundle = create_proposal(client, "ch", "mycc", [b"put", b"k1", b"v1"])
    signed = create_signed_proposal(bundle, client)
    resp = endorser.process_proposal(signed)
    assert resp.response.status == 200, resp.response.message
    assert resp.endorsement.signature
    # the endorsement must verify and the rwset must contain the write
    prp = protoutil.unmarshal(peer_pb2.ProposalResponsePayload, resp.payload)
    action = protoutil.unmarshal(peer_pb2.ChaincodeAction, prp.extension)
    rwset = parse_tx_rwset(action.results)
    assert KVWrite("k1", False, b"v1") in rwset.ns_rw_sets[0].writes
    # signable by create_signed_tx (client assembles the envelope)
    env = create_signed_tx(bundle, client, [resp])
    assert env.signature


@requires_crypto
def test_process_proposal_rejects_bad_signature(endorser_net, org):
    endorser, client, _ = endorser_net
    bundle = create_proposal(client, "ch", "mycc", [b"get", b"a"])
    signed = create_signed_proposal(bundle, client)
    signed.signature = signed.signature[:-1] + bytes(
        [signed.signature[-1] ^ 1]
    )
    resp = endorser.process_proposal(signed)
    assert resp.response.status == 500
    assert "access denied" in resp.response.message


@requires_crypto
def test_process_proposal_rejects_wrong_txid(endorser_net):
    endorser, client, _ = endorser_net
    bundle = create_proposal(client, "ch", "mycc", [b"get", b"a"])
    chdr = protoutil.unmarshal(
        __import__(
            "fabric_tpu.protos.common_pb2", fromlist=["ChannelHeader"]
        ).ChannelHeader,
        bundle.channel_header,
    )
    chdr.tx_id = "beef"
    bundle.channel_header = chdr.SerializeToString()
    signed = create_signed_proposal(bundle, client)
    resp = endorser.process_proposal(signed)
    assert resp.response.status == 500
    assert "txid" in resp.response.message


@requires_crypto
def test_process_proposal_unknown_channel(endorser_net):
    endorser, client, _ = endorser_net
    bundle = create_proposal(client, "nochannel", "mycc", [b"get", b"a"])
    signed = create_signed_proposal(bundle, client)
    resp = endorser.process_proposal(signed)
    assert resp.response.status == 500
    assert "not found" in resp.response.message


@requires_crypto
def test_process_proposal_chaincode_error_unsigned(endorser_net):
    endorser, client, _ = endorser_net
    bundle = create_proposal(client, "ch", "mycc", [b"nope"])
    signed = create_signed_proposal(bundle, client)
    resp = endorser.process_proposal(signed)
    assert resp.response.status == 500
    assert not resp.endorsement.signature


@requires_crypto
def test_process_proposal_malformed_bytes_returns_500(endorser_net):
    endorser, _, _ = endorser_net
    signed = peer_pb2.SignedProposal()
    signed.proposal_bytes = b"\xff\xff\xff garbage"
    resp = endorser.process_proposal(signed)
    assert resp.response.status == 500
    assert "unmarshalling" in resp.response.message


@requires_crypto
def test_unpack_proposal_rejects_missing_chaincode(endorser_net):
    _, client, _ = endorser_net
    bundle = create_proposal(client, "ch", "mycc", [b"x"])
    signed = create_signed_proposal(bundle, client)
    prop = protoutil.unmarshal(peer_pb2.Proposal, signed.proposal_bytes)
    from fabric_tpu.protos import common_pb2

    header = protoutil.unmarshal(common_pb2.Header, prop.header)
    chdr = protoutil.unmarshal(common_pb2.ChannelHeader, header.channel_header)
    chdr.extension = peer_pb2.ChaincodeHeaderExtension().SerializeToString()
    header.channel_header = chdr.SerializeToString()
    prop.header = header.SerializeToString()
    signed.proposal_bytes = prop.SerializeToString()
    with pytest.raises(ProposalError):
        unpack_proposal(signed)
