"""Deliver-client endpoint failover under the shared retry/backoff
helper and seeded fault plans (fabchaos satellite): endpoint 1 flaps N
times, retries are bounded and policy-paced, the total-delay deadline is
honored, and delivery resumes on endpoint 2."""

from typing import List

import pytest

from fabric_tpu.common.faults import FaultPlan, plan_installed
from fabric_tpu.common.retry import RetryPolicy
from fabric_tpu.deliver.client import BlockDeliverer
from fabric_tpu.protos import ab_pb2, common_pb2, protoutil
from fabric_tpu.tools.fabchaos import _seek_start


def _blocks(n: int) -> List[common_pb2.Block]:
    return [protoutil.new_block(i, b"") for i in range(n)]


def _endpoint(name: str, blocks, calls: List[str]):
    def serve(env):
        calls.append(name)
        for b in blocks[_seek_start(env):]:
            resp = ab_pb2.DeliverResponse()
            resp.block.CopyFrom(b)
            yield resp

    return serve


def _deliverer(blocks, calls, got, sleeps, endpoints=2, **kw):
    eps = [_endpoint(f"ep{i}", blocks, calls) for i in range(endpoints)]
    kw.setdefault(
        "retry_policy",
        RetryPolicy(base_s=0.05, multiplier=2.0, cap_s=0.4, deadline_s=30.0),
    )
    return BlockDeliverer(
        "testchan",
        eps,
        on_block=lambda b: got.append(b.header.number),
        next_block=lambda: len(got),
        sleeper=lambda s: sleeps.append(round(s, 6)),
        **kw,
    )


def test_flap_then_failover_resumes_on_endpoint_2():
    blocks = _blocks(6)
    calls, got, sleeps = [], [], []
    flap_n = 3
    with plan_installed(
        FaultPlan.parse(f"deliver.pull=raise:1.0:max={flap_n}", seed=1)
    ):
        d = _deliverer(blocks, calls, got, sleeps)
        received = d.run(max_blocks=6)
    assert received == 6
    assert got == [0, 1, 2, 3, 4, 5]
    # bounded retries: exactly one backoff sleep per flap, on the ramp
    assert sleeps == [0.05, 0.1, 0.2]
    # attempts 1..3 flapped and failed over each time; with 2 endpoints
    # attempt 4 lands on ep1 (index 3 % 2) and serves the whole range
    assert calls == ["ep1"]


def test_backoff_resets_after_successful_block():
    """A flap AFTER progress restarts the exponential ramp (the
    reference resets its failure counter per delivered block)."""
    blocks = _blocks(4)
    calls, got, sleeps = [], [], []
    # attempts 1 and 3 fail: 1 flap, serve blocks, mid-stream failure
    # is simulated by max_blocks-ing two sessions
    with plan_installed(
        FaultPlan.parse("deliver.pull=raise:1.0:max=1", seed=1)
    ):
        d = _deliverer(blocks, calls, got, sleeps)
        assert d.run(max_blocks=2) == 2
    with plan_installed(
        FaultPlan.parse("deliver.pull=raise:1.0:max=1", seed=1)
    ):
        # fresh deliverer, same ramp start: the Backoff reset means the
        # second session's first retry is base_s again, not the ramp tail
        d2 = _deliverer(blocks, calls, got, sleeps)
        assert d2.run(max_blocks=2) == 2
    assert got == [0, 1, 2, 3]
    assert sleeps == [0.05, 0.05]


def test_deadline_honored_when_all_endpoints_dead():
    blocks = _blocks(2)
    calls, got, sleeps = [], [], []
    with plan_installed(FaultPlan.parse("deliver.pull=raise:1.0", seed=1)):
        d = _deliverer(
            blocks, calls, got, sleeps,
            retry_policy=RetryPolicy(
                base_s=0.05, multiplier=2.0, cap_s=0.4, deadline_s=1.0
            ),
        )
        received = d.run(max_blocks=2)
    assert received == 0 and got == []
    # nominal sleep budget: 0.05+0.1+0.2+0.4 = 0.75; adding the next
    # 0.4 would breach the 1.0s deadline, so the session ends there
    assert sleeps == [0.05, 0.1, 0.2, 0.4]
    assert sum(sleeps) <= 1.0


def test_max_attempts_bounds_retries():
    blocks = _blocks(2)
    calls, got, sleeps = [], [], []
    with plan_installed(FaultPlan.parse("deliver.pull=raise:1.0", seed=1)):
        d = _deliverer(
            blocks, calls, got, sleeps,
            retry_policy=RetryPolicy(
                base_s=0.01, multiplier=2.0, cap_s=1.0, deadline_s=60.0,
                max_attempts=3,
            ),
        )
        assert d.run(max_blocks=2) == 0
    assert len(sleeps) == 3


def test_legacy_constructor_args_still_shape_the_policy():
    """max_retry_delay/max_total_delay (the pre-retry.py surface) keep
    working: they cap the per-sleep delay and the total budget."""
    blocks = _blocks(1)
    calls, got, sleeps = [], [], []
    with plan_installed(FaultPlan.parse("deliver.pull=raise:1.0", seed=1)):
        d = BlockDeliverer(
            "testchan",
            [_endpoint("ep0", blocks, calls)],
            on_block=lambda b: got.append(b.header.number),
            next_block=lambda: len(got),
            sleeper=lambda s: sleeps.append(s),
            max_retry_delay=0.08,
            max_total_delay=0.3,
        )
        assert d.run(max_blocks=1) == 0
    assert sleeps and max(sleeps) <= 0.08
    assert sum(sleeps) <= 0.3


def test_clean_path_unchanged_without_plan():
    blocks = _blocks(5)
    calls, got, sleeps = [], [], []
    d = _deliverer(blocks, calls, got, sleeps)
    assert d.run(max_blocks=5) == 5
    assert sleeps == [] and calls == ["ep0"]
    assert d.stats.failures == 0


def test_update_endpoints_midstream_with_faults():
    """A config refresh lands new endpoints while the old primary is
    flapping: the pull resumes on the refreshed list."""
    blocks = _blocks(4)
    calls, got, sleeps = [], [], []
    fresh_calls: List[str] = []
    with plan_installed(
        FaultPlan.parse("deliver.pull=raise:1.0:max=2", seed=1)
    ):
        d = _deliverer(blocks, calls, got, sleeps, endpoints=1)
        # refresh as soon as the first backoff sleep happens
        orig_sleeper = d._sleeper

        def refresh_then_sleep(s):
            d.update_endpoints([_endpoint("fresh", blocks, fresh_calls)])
            orig_sleeper(s)

        d._sleeper = refresh_then_sleep
        assert d.run(max_blocks=4) == 4
    assert got == [0, 1, 2, 3]
    assert fresh_calls == ["fresh"]


def test_retry_seed_arms_jitter_on_default_policy():
    """retry_seed alone (no custom policy) must actually desynchronize
    the ramp: ±20% seeded jitter on the reference policy."""
    blocks = _blocks(1)
    calls, got, sleeps = [], [], []
    with plan_installed(FaultPlan.parse("deliver.pull=raise:1.0:max=4", seed=1)):
        d = BlockDeliverer(
            "testchan",
            [_endpoint("ep0", blocks, calls)],
            on_block=lambda b: got.append(b.header.number),
            next_block=lambda: len(got),
            sleeper=lambda s: sleeps.append(s),
            retry_seed=42,
        )
        assert d.run(max_blocks=1) == 1
    assert d._retry_policy.jitter == 0.2
    base_ramp = [0.06 * 1.2**i for i in range(4)]
    assert any(abs(s - b) > 1e-9 for s, b in zip(sleeps, base_ramp))
    for s, b in zip(sleeps, base_ramp):
        assert 0.8 * b - 1e-9 <= s <= 1.2 * b + 1e-9
    # seeded: a second deliverer with the same seed replays identically
    sleeps2 = []
    with plan_installed(FaultPlan.parse("deliver.pull=raise:1.0:max=4", seed=1)):
        d2 = BlockDeliverer(
            "testchan",
            [_endpoint("ep0", blocks, [])],
            on_block=lambda b: None,
            next_block=lambda: 0,
            sleeper=lambda s: sleeps2.append(s),
            retry_seed=42,
        )
        d2.run(max_blocks=1)
    assert sleeps2[: len(sleeps)] == sleeps
