"""hostbn differential suite: the numpy limb-matrix FP256BN engine vs
the fp256bn Python-int oracle — tower kernels on dense-limb and
modulus-edge operands, pairing bilinearity and structure-check masks,
batched MSM (every degenerate-lane flavor), tree-inversion edge lanes,
the idemix batch rung's bit-exact mask vs scheme.verify_signature, the
process-pool shard path (+ degrade-to-inline), and the numpy-absent
ladder walk (same checklist shape as tests/test_hostec_np.py)."""

import random
import subprocess
import sys

import pytest

from fabric_tpu.common import fp256bn as host
from fabric_tpu.crypto import hostbn as hb

pytestmark = pytest.mark.skipif(
    not hb.HAVE_NUMPY, reason="hostbn needs numpy"
)

if hb.HAVE_NUMPY:
    import numpy as np

    from fabric_tpu.crypto.hostec_np import (
        _FE,
        _Field,
        _ctx,
        _invert_lanes,
        ints_to_limbs13,
        limbs13_to_pairs,
        _pairs_to_int,
    )

P = host.P
R = host.R
RNG = random.Random(20260803)

# dense-limb / modulus-edge Fp operands (the test convention from
# tests/test_bignum.py: every pair limb saturated, and values hugging p)
EDGE_VALUES = [0, 1, 2, P - 1, P - 2, (1 << 256) % P, int("3" * 77) % P]
DENSE = int("0x" + "f" * 64, 16) % P


def _field():
    return _Field(_ctx(P))


def _v_from_host(field, rows_per_lane):
    lanes = len(rows_per_lane)
    k = len(rows_per_lane[0])
    flat = []
    for r in range(k):
        flat.extend(
            (rows_per_lane[lane][r] * hb.R_MONT) % P for lane in range(lanes)
        )
    pairs = limbs13_to_pairs(ints_to_limbs13(flat))
    return hb._V(
        _FE(np.ascontiguousarray(pairs), 1, hb.PAIR_MASK), k, lanes
    )


def _v_to_host(field, v):
    out = field.to_ints(field.carried(v.fe))
    return [
        [out[r * v.lanes + lane] for r in range(v.k)]
        for lane in range(v.lanes)
    ]


def _fp12_rows(x):
    rows = []
    for c in x:
        rows.extend([c[0], c[1]])
    return rows


def _rows_fp12(rows):
    return tuple((rows[2 * i], rows[2 * i + 1]) for i in range(6))


def _rand_fp12(rng):
    return tuple((rng.randrange(P), rng.randrange(P)) for _ in range(6))


# ---------------------------------------------------------------------------
# Hard-part decomposition + tower kernels vs the oracle
# ---------------------------------------------------------------------------


def test_hard_exp_decomposition_exact():
    """The λ x-power chain is only bit-exact with fp12_pow(s, HARD)
    because the decomposition is EXACT — re-assert the integer identity
    the module checks at import."""
    x = host.U
    lam0 = -36 * x**3 - 30 * x**2 - 18 * x - 2
    lam1 = -36 * x**3 - 18 * x**2 - 12 * x + 1
    lam2 = 6 * x**2 + 1
    assert lam0 + lam1 * P + lam2 * P**2 + P**3 == host._HARD_EXP
    assert (P**4 - P**2 + 1) % R == 0


def test_fp12_tower_ops_vs_oracle():
    """mul/sqr/conj/frobenius/inv bit-exact with the host tower on
    random, dense-limb and modulus-edge lanes (zero lane included for
    the inversion's pow(0) = 0 contract)."""
    field = _field()
    rng = random.Random(7)
    lanes = [
        _rand_fp12(rng),
        tuple((DENSE, P - 1) for _ in range(6)),  # dense / edge limbs
        tuple((EDGE_VALUES[i], EDGE_VALUES[-1 - i]) for i in range(6)),
    ]
    ys = [_rand_fp12(rng) for _ in lanes]
    vx = _v_from_host(field, [_fp12_rows(x) for x in lanes])
    vy = _v_from_host(field, [_fp12_rows(y) for y in ys])

    got = [_rows_fp12(r) for r in _v_to_host(field, hb._fp12_mul(field, vx, vy))]
    assert got == [host.fp12_mul(x, y) for x, y in zip(lanes, ys)]

    got = [_rows_fp12(r) for r in _v_to_host(field, hb._fp12_sqr(field, vx))]
    assert got == [host.fp12_sqr(x) for x in lanes]

    got = [_rows_fp12(r) for r in _v_to_host(field, hb._fp12_conj(field, vx))]
    assert got == [host.fp12_conj(x) for x in lanes]

    for n in (1, 2, 3):
        got = [
            _rows_fp12(r)
            for r in _v_to_host(field, hb._fp12_frob(field, vx, n))
        ]
        assert got == [host.fp12_frobenius(x, n) for x in lanes]

    zlanes = lanes + [tuple((0, 0) for _ in range(6))]
    vz = _v_from_host(field, [_fp12_rows(x) for x in zlanes])
    got = [_rows_fp12(r) for r in _v_to_host(field, hb._fp12_inv(field, vz))]
    assert got == [host.fp12_inv(x) for x in zlanes]


def test_fp12_squaring_chain_edge_operands():
    """8 chained squarings starting from dense-limb/edge operands stay
    bit-exact (the lazy-bound renormalization discipline under
    repeated composition — the shape tests/test_bignum.py pins for the
    device kernels)."""
    field = _field()
    start = [
        tuple((DENSE, P - 1) for _ in range(6)),
        tuple((P - 2, 1) for _ in range(6)),
    ]
    v = _v_from_host(field, [_fp12_rows(x) for x in start])
    want = list(start)
    for _ in range(8):
        v = hb._fp12_sqr(field, v)
        want = [host.fp12_sqr(x) for x in want]
    assert [_rows_fp12(r) for r in _v_to_host(field, v)] == want


def test_tree_inversion_zero_and_odd_tails():
    """_invert_lanes over the BN modulus: zero lanes come back zero
    without poisoning the tree, odd widths keep their tail lane."""
    field = _field()
    for width in (1, 2, 3, 5, 7):
        vals = [RNG.randrange(1, P) for _ in range(width)]
        if width >= 3:
            vals[1] = 0  # a zero lane mid-tree
        mont = [(v * hb.R_MONT) % P for v in vals]
        fe = _FE(
            np.ascontiguousarray(limbs13_to_pairs(ints_to_limbs13(mont))),
            1,
            hb.PAIR_MASK,
        )
        inv = field.to_ints(_invert_lanes(field, fe))
        for v, got in zip(vals, inv):
            assert got == (pow(v, P - 2, P) if v else 0)


# ---------------------------------------------------------------------------
# Pairing structure check
# ---------------------------------------------------------------------------


def _oracle_check(w, a_prime, a_bar):
    t = host.fp12_mul(
        host.ate(w, a_prime), host.fp12_inv(host.ate(host.G2_GEN, a_bar))
    )
    return host.gt_is_unity(host.fexp(t))


@pytest.fixture(scope="module")
def pairing_world():
    rng = random.Random(99)
    sk = rng.randrange(R)
    w = host.g2_mul(host.G2_GEN, sk)
    hb.warm_schedules(w)
    return rng, sk, w


def test_pairing_check_mask_vs_oracle(pairing_world):
    """The fused two-pairing batch agrees with the oracle verdict on
    valid, mismatched, identity-ABar and invalid-lane flavors."""
    rng, sk, w = pairing_world
    a = host.g1_mul(host.G1_GEN, rng.randrange(1, R))
    abar = host.g1_mul(a, sk)
    other = host.g1_mul(host.G1_GEN, rng.randrange(1, R))
    pairs = [
        (a, abar),        # valid structure
        (a, other),       # wrong ABar
        (other, abar),    # wrong A'
        None,             # pre-parse invalid lane
        (a, None),        # identity ABar (miller = ONE in the oracle)
    ]
    got = hb.pairing_check_batch(w, pairs)
    want = [
        p is not None and _oracle_check(w, p[0], p[1]) for p in pairs
    ]
    assert got == want
    assert got == [True, False, False, False, False]


def test_pairing_bilinearity_spot(pairing_world):
    """Bilinearity through the public check: with W = s·G2,
    e(W, b·G1) == e(G2, sb·G1) for fresh (s, b) — and shifting either
    side by one breaks it."""
    rng, sk, w = pairing_world
    b = rng.randrange(2, R)
    pt = host.g1_mul(host.G1_GEN, b)
    good = host.g1_mul(host.G1_GEN, (sk * b) % R)
    off = host.g1_mul(host.G1_GEN, (sk * b + 1) % R)
    assert hb.pairing_check_batch(w, [(pt, good), (pt, off)]) == [
        True,
        False,
    ]


# ---------------------------------------------------------------------------
# Batched MSM
# ---------------------------------------------------------------------------


def _oracle_msm(bases, scalars):
    acc = None
    for b, s in zip(bases, scalars):
        acc = host.g1_add(acc, host.g1_mul(b, s))
    return acc


def test_msm_batch_vs_oracle_mixed_jobs():
    """Mixed base counts, identity bases, zero and order-edge scalars,
    P + (−P) cancellation and duplicate bases (the P = Q patch path at
    the slot-reduction level) — all against the affine oracle."""
    rng = random.Random(5)
    pts = [host.g1_mul(host.G1_GEN, rng.randrange(1, R)) for _ in range(6)]
    pt = pts[0]
    jobs = [
        # generic jobs with differing K (exercises the K-grouping)
        ([pts[1], pts[2], pts[3]], [rng.randrange(R) for _ in range(3)]),
        (
            [pts[i % 6] for i in range(8)],
            [rng.randrange(R) for _ in range(8)],
        ),
        # identity base slot + zero scalar
        ([pts[4], None, pts[5]], [rng.randrange(R), 7, 0]),
        # order-edge scalars
        ([pts[1], pts[2]], [R - 1, 1]),
        # identity result: P + (−P)
        ([pt, host.g1_neg(pt)], [1, 1]),
        # duplicate base: slot reduction adds P = Q
        ([pt, pt], [9, 9]),
        # all-zero job -> identity
        ([pts[3]], [0]),
    ]
    got = hb.msm_batch(jobs)
    want = [_oracle_msm(b, s) for b, s in jobs]
    assert got == want
    assert got[4] is None and got[6] is None


# ---------------------------------------------------------------------------
# Idemix batch rung: mask vs the scheme oracle, pool path, ladder
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def idemix_world():
    from fabric_tpu import idemix
    from fabric_tpu.protos import idemix_pb2

    rng = random.Random(7)
    attrs = ["OU", "Role", "EnrollmentID", "RevocationHandle"]
    rh_index = 3
    ik = idemix.new_issuer_key(attrs, rng)
    sk = host.rand_mod_order(rng)
    nonce = host.big_to_bytes(host.rand_mod_order(rng))
    req = idemix.new_cred_request(sk, nonce, ik.ipk, rng)
    cred = idemix.new_credential(ik, req, [11, 22, 33, 44], rng)
    cri = idemix_pb2.CredentialRevocationInformation()
    cri.revocation_alg = idemix.ALG_NO_REVOCATION

    def sign(disclosure, msg):
        nym, r_nym = idemix.make_nym(sk, ik.ipk, rng)
        return idemix.new_signature(
            cred, sk, nym, r_nym, ik.ipk, disclosure, msg, rh_index, cri, rng
        )

    return ik, sign, rh_index


def _flavor_lanes(idemix_world):
    """(sigs, disclosures, msgs, values): valid lanes plus every
    invalid flavor the ISSUE names."""
    from fabric_tpu.protos import idemix_pb2

    ik, sign, rh_index = idemix_world
    hid, dis = [0, 0, 0, 0], [0, 1, 0, 0]
    s0 = sign(hid, b"m0")
    s1 = sign(dis, b"m1")

    def variant(base, mutate):
        sig = idemix_pb2.Signature()
        sig.CopyFrom(base)
        mutate(sig)
        return sig

    def bump(field):
        def mutate(sig):
            v = host.big_from_bytes(getattr(sig, field))
            setattr(sig, field, host.big_to_bytes((v + 1) % R))
        return mutate

    def off_curve(sig):
        sig.a_bar.x = host.big_to_bytes(3)
        sig.a_bar.y = host.big_to_bytes(4)

    def ident_abar(sig):
        sig.a_bar.x = host.big_to_bytes(0)
        sig.a_bar.y = host.big_to_bytes(0)

    lanes = [
        (s0, hid, b"m0", [None] * 4),                      # valid
        (s1, dis, b"m1", [None, 22, None, None]),          # valid disclosed
        (s0, hid, b"WRONG", [None] * 4),                   # bad challenge
        (variant(s0, bump("proof_s_sk")), hid, b"m0", [None] * 4),
        (variant(s1, bump("proof_c")), dis, b"m1", [None, 22, None, None]),
        (s1, dis, b"m1", [None, 999, None, None]),         # wrong commitment
        (variant(s0, off_curve), hid, b"m0", [None] * 4),  # off-group point
        (variant(s0, ident_abar), hid, b"m0", [None] * 4),
    ]
    return (
        [l[0] for l in lanes],
        [l[1] for l in lanes],
        [l[2] for l in lanes],
        [l[3] for l in lanes],
        rh_index,
        ik.ipk,
    )


@pytest.fixture(scope="module")
def flavor_batch(idemix_world):
    from fabric_tpu.idemix.batch import verify_signatures_batch

    sigs, disc, msgs, values, rh_index, ipk = _flavor_lanes(idemix_world)
    oracle = verify_signatures_batch(
        sigs, disc, ipk, msgs, values, rh_index, backend="scheme"
    )
    assert oracle == [True, True, False, False, False, False, False, False]
    return sigs, disc, msgs, values, rh_index, ipk, oracle


def test_batch_mask_bit_exact_vs_oracle(flavor_batch):
    from fabric_tpu.idemix.batch import verify_signatures_batch

    sigs, disc, msgs, values, rh_index, ipk, oracle = flavor_batch
    got = verify_signatures_batch(
        sigs, disc, ipk, msgs, values, rh_index, backend="hostbn"
    )
    assert got == oracle


def test_batch_routes_through_active_ladder(flavor_batch):
    """backend=None follows bccsp's ladder — hostbn here (numpy is
    installed) — and yields the oracle mask."""
    from fabric_tpu.crypto.bccsp import idemix_backend_name
    from fabric_tpu.idemix.batch import verify_signatures_batch

    sigs, disc, msgs, values, rh_index, ipk, oracle = flavor_batch
    assert idemix_backend_name() == "hostbn"
    got = verify_signatures_batch(sigs, disc, ipk, msgs, values, rh_index)
    assert got == oracle


def test_pool_path_and_degrade_inline(flavor_batch, monkeypatch):
    """The shared-nothing pool shards the batch (order-preserving) and
    a submit-time fault degrades to inline compute with the SAME mask
    — degrade, never die."""
    from fabric_tpu.common.faults import FaultPlan, plan_installed
    from fabric_tpu.idemix import batch as ib

    sigs, disc, msgs, values, rh_index, ipk, oracle = flavor_batch
    # tile to 16 lanes and force the pool on at that size
    tiled = [sigs[i % len(sigs)] for i in range(16)]
    tdisc = [disc[i % len(sigs)] for i in range(16)]
    tmsgs = [msgs[i % len(sigs)] for i in range(16)]
    tvals = [values[i % len(sigs)] for i in range(16)]
    texp = [oracle[i % len(sigs)] for i in range(16)]
    monkeypatch.setenv("FABRIC_TPU_HOSTBN_MIN_POOL", "8")
    monkeypatch.setenv("FABRIC_TPU_HOSTBN_MIN_SHARD", "8")
    monkeypatch.setenv("FABRIC_TPU_HOSTBN_PROCS", "2")
    try:
        got = ib.verify_signatures_batch(
            tiled, tdisc, ipk, tmsgs, tvals, rh_index, backend="hostbn"
        )
        assert got == texp
        # injected submit failure: inline fallback, same mask, pool torn
        plan = FaultPlan.parse("hostbn.pool.submit=raise:1.0", seed=3)
        with plan_installed(plan):
            got = ib.verify_signatures_batch(
                tiled, tdisc, ipk, tmsgs, tvals, rh_index, backend="hostbn"
            )
        assert got == texp
        assert plan.fired().get("hostbn.pool.submit", 0) >= 1
    finally:
        ib.shutdown_pool()


def test_idemix_verdict_corrupt_seam(flavor_batch):
    """The idemix.verdict corrupt site flips exactly the planned lanes
    — the seam the chaos mask gate proves itself against."""
    from fabric_tpu.common.faults import FaultPlan, plan_installed
    from fabric_tpu.idemix.batch import verify_signatures_batch

    sigs, disc, msgs, values, rh_index, ipk, oracle = flavor_batch
    plan = FaultPlan.parse("idemix.verdict=corrupt:1.0:lanes=1", seed=5)
    with plan_installed(plan):
        got = verify_signatures_batch(
            sigs, disc, ipk, msgs, values, rh_index, backend="hostbn"
        )
    assert sum(1 for a, b in zip(got, oracle) if a != b) == 1


def test_idemix_verdict_fires_once_not_in_pool_workers(flavor_batch):
    """The corrupt seam fires ONCE per batch, in the coordinating
    process: the worker re-entry (_pool_ok=False) must NOT apply an
    inherited plan, or shard flips and the parent's flips would cancel
    and an armed fault could become a silent no-op."""
    from fabric_tpu.common.faults import FaultPlan, plan_installed
    from fabric_tpu.idemix.batch import verify_signatures_batch

    sigs, disc, msgs, values, rh_index, ipk, oracle = flavor_batch
    plan = FaultPlan.parse("idemix.verdict=corrupt:1.0", seed=5)
    with plan_installed(plan):
        worker_view = verify_signatures_batch(
            sigs, disc, ipk, msgs, values, rh_index,
            backend="hostbn", _pool_ok=False,
        )
    assert worker_view == oracle  # uncorrupted inside the worker path


# ---------------------------------------------------------------------------
# Ladder selection / numpy-absent degradation
# ---------------------------------------------------------------------------


def test_ladder_pin_and_auto(monkeypatch):
    """Explicit pins honored; with numpy 'absent' the auto walk lands
    on the scheme rung and a hostbn pin raises ImportError."""
    from fabric_tpu.crypto import bccsp

    before = bccsp.idemix_backend_name()
    try:
        assert bccsp.select_idemix_backend("hostbn") is hb
        assert bccsp.idemix_backend_name() == "hostbn"
        assert bccsp.select_idemix_backend("scheme") is None
        assert bccsp.idemix_backend_name() == "scheme"
        with pytest.raises(ValueError):
            bccsp.select_idemix_backend("nope")
        monkeypatch.setattr(hb, "HAVE_NUMPY", False)
        assert bccsp.select_idemix_backend("auto") is None
        assert bccsp.idemix_backend_name() == "scheme"
        with pytest.raises(ImportError):
            bccsp.select_idemix_backend("hostbn")
    finally:
        monkeypatch.setattr(hb, "HAVE_NUMPY", True)
        bccsp.select_idemix_backend(before)


def test_env_pin_malformed_warns_never_raises(monkeypatch):
    from fabric_tpu.crypto import bccsp

    before = bccsp.idemix_backend_name()
    monkeypatch.setenv("FABRIC_TPU_IDEMIX_BACKEND", "bogus-tier")
    try:
        with pytest.warns(RuntimeWarning):
            bccsp.select_idemix_backend("auto")
        assert bccsp.idemix_backend_name() in ("hostbn", "scheme")
    finally:
        monkeypatch.delenv("FABRIC_TPU_IDEMIX_BACKEND", raising=False)
        bccsp.select_idemix_backend(before)


def test_factory_idemix_backend(monkeypatch):
    """BCCSP.SW.IdemixBackend: known tiers select; unknown names warn
    and keep the pin; a known-but-unavailable tier errors HARD."""
    from fabric_tpu.crypto import bccsp, factory

    before = bccsp.idemix_backend_name()
    try:
        factory.provider_from_config(
            {"Default": "SW", "SW": {"IdemixBackend": "scheme"}}
        )
        assert bccsp.idemix_backend_name() == "scheme"
        factory.provider_from_config(
            {"Default": "SW", "SW": {"IdemixBackend": "hostbn"}}
        )
        assert bccsp.idemix_backend_name() == "hostbn"
        # unknown name: keep the current selection, never raise
        factory.provider_from_config(
            {"Default": "SW", "SW": {"IdemixBackend": "hostbn_v99"}}
        )
        assert bccsp.idemix_backend_name() == "hostbn"
        monkeypatch.setattr(hb, "HAVE_NUMPY", False)
        with pytest.raises(factory.FactoryError):
            factory.provider_from_config(
                {"Default": "SW", "SW": {"IdemixBackend": "hostbn"}}
            )
    finally:
        monkeypatch.setattr(hb, "HAVE_NUMPY", True)
        bccsp.select_idemix_backend(before)


def test_module_imports_without_numpy_subprocess():
    """hostbn (and the idemix ladder around it) must import with numpy
    genuinely blocked, walking to the scheme rung with a warning in the
    log — the guarded-import discipline the collect gate relies on."""
    code = (
        "import sys\n"
        "sys.modules['numpy'] = None\n"  # import numpy -> ImportError
        "import fabric_tpu.crypto.hostbn as hb\n"
        "assert not hb.HAVE_NUMPY\n"
        "from fabric_tpu.crypto import bccsp\n"
        "assert bccsp.select_idemix_backend('auto') is None\n"
        "assert bccsp.idemix_backend_name() == 'scheme'\n"
        "assert bccsp.available_idemix_backends() == "
        "{'hostbn': False, 'scheme': True}\n"
        "print('ok')\n"
    )
    res = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr
    assert "ok" in res.stdout
