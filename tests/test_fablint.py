"""fablint: one firing fixture per rule, negative controls, suppression,
generated-file exclusion, CLI plumbing, and the repo self-check (the CI
gate invariant: ``fablint fabric_tpu/`` reports 0 violations)."""

import json
import textwrap
from pathlib import Path

import pytest

from fabric_tpu.tools import fablint

REPO_ROOT = Path(__file__).resolve().parent.parent


def lint(src: str, path: str = "fabric_tpu/common/fixture.py", rules=None):
    findings, _ = fablint.lint_source(textwrap.dedent(src), path, rules)
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# rule fixtures: each rule fires on its minimal counterexample
# ---------------------------------------------------------------------------


def test_module_import_fires_on_unguarded_heavy_import():
    findings = lint("import jax\n", path="fabric_tpu/msp/fixture.py",
                    rules=["module-import"])
    assert rule_ids(findings) == ["module-import"]
    assert findings[0].line == 1


def test_module_import_from_form_and_submodule():
    findings = lint(
        "from cryptography.hazmat.primitives import hashes\n",
        path="fabric_tpu/msp/fixture.py", rules=["module-import"],
    )
    assert rule_ids(findings) == ["module-import"]


def test_module_import_guarded_or_lazy_is_clean():
    src = """
    try:
        import grpc
    except ImportError:
        grpc = None

    def lazy():
        import jax
        return jax
    """
    assert lint(src, path="fabric_tpu/msp/fixture.py",
                rules=["module-import"]) == []


def test_module_import_allowlist():
    # the kernel layer imports jax at module scope by design
    assert lint("import jax\n", path="fabric_tpu/ops/fixture.py",
                rules=["module-import"]) == []


def test_broad_except_bare_fires_anywhere():
    src = """
    try:
        x = 1
    except:
        pass
    """
    findings = lint(src, path="fabric_tpu/gossip/fixture.py",
                    rules=["broad-except"])
    assert rule_ids(findings) == ["broad-except"]


def test_broad_except_swallow_fires_in_mask_critical_path():
    src = """
    try:
        verify()
    except Exception:
        pass
    """
    findings = lint(src, path="fabric_tpu/crypto/fixture.py",
                    rules=["broad-except"])
    assert rule_ids(findings) == ["broad-except"]


def test_broad_except_that_logs_or_reraises_is_clean():
    src = """
    try:
        verify()
    except Exception as exc:
        logger.warning("verify failed: %s", exc)
    try:
        verify()
    except Exception:
        raise
    """
    assert lint(src, path="fabric_tpu/validation/fixture.py",
                rules=["broad-except"]) == []


def test_broad_except_unrelated_log_leaf_still_fires():
    # math.log()/obj.error() must not be mistaken for logging
    src = """
    try:
        verify()
    except Exception:
        y = math.log(2)
    try:
        verify()
    except Exception:
        obj.error()
    """
    findings = lint(src, path="fabric_tpu/crypto/fixture.py",
                    rules=["broad-except"])
    assert rule_ids(findings) == ["broad-except", "broad-except"]


def test_broad_except_logger_factory_chain_counts_as_logging():
    src = """
    try:
        verify()
    except Exception as exc:
        flogging.must_get_logger("validation").warning("no: %s", exc)
    try:
        verify()
    except Exception as exc:
        self._log.debug("no: %s", exc)
    """
    assert lint(src, path="fabric_tpu/validation/fixture.py",
                rules=["broad-except"]) == []


def test_broad_except_outside_mask_critical_path_is_clean():
    src = """
    try:
        tick()
    except Exception:
        pass
    """
    assert lint(src, path="fabric_tpu/gossip/fixture.py",
                rules=["broad-except"]) == []


def test_mutable_default_fires():
    findings = lint("def f(x=[], *, y={}):\n    return x, y\n",
                    rules=["mutable-default"])
    assert rule_ids(findings) == ["mutable-default", "mutable-default"]


def test_mutable_default_none_sentinel_is_clean():
    assert lint("def f(x=None, y=()):\n    return x\n",
                rules=["mutable-default"]) == []


# The jit-impure firing fixtures moved to tests/test_fabtrace.py in
# PR 18 (behavior-pinned) when the rule migrated to fabtrace's
# traced-body dataflow.


def test_jit_impure_is_retired_from_fablint():
    assert "jit-impure" not in fablint.RULES
    assert lint(
        "@jax.jit\ndef kernel(x):\n    print(x)\n    return x\n",
        path="fabric_tpu/ops/fixture.py",
    ) == []


def test_limb_dtype_fires_without_dtype():
    findings = lint("x = jnp.array([0xFFFFFFFF00000001])\n",
                    rules=["limb-dtype"])
    assert rule_ids(findings) == ["limb-dtype"]


def test_limb_dtype_explicit_dtype_or_small_literal_is_clean():
    src = """
    x = jnp.array([0xFFFFFFFF00000001], dtype=jnp.uint64)
    y = np.array([0xFFFF])
    z = np.array([0xFFFFFFFF00000001], np.uint64)  # positional dtype
    w = np.array([0xFFFFFFFF00000001], object)
    """
    assert lint(src, rules=["limb-dtype"]) == []


def test_assert_security_fires_in_crypto():
    findings = lint("assert sig_ok\n", path="fabric_tpu/crypto/fixture.py",
                    rules=["assert-security"])
    assert rule_ids(findings) == ["assert-security"]


def test_assert_security_outside_scope_is_clean():
    assert lint("assert cache_ok\n", path="fabric_tpu/gossip/fixture.py",
                rules=["assert-security"]) == []


def test_digest_compare_fires():
    findings = lint("ok = computed_digest == expected\n",
                    rules=["digest-compare"])
    assert rule_ids(findings) == ["digest-compare"]


def test_digest_compare_none_check_and_plain_names_are_clean():
    src = """
    a = digest == None
    b = count == other_count
    """
    assert lint(src, rules=["digest-compare"]) == []


def test_shell_injection_fires():
    src = """
    subprocess.run("ls /", shell=True)
    os.system("ls /")
    """
    findings = lint(src, rules=["shell-injection"])
    assert rule_ids(findings) == ["shell-injection", "shell-injection"]


def test_shell_injection_argv_list_is_clean():
    assert lint('subprocess.run(["ls", "/"], check=True)\n',
                rules=["shell-injection"]) == []


def test_fork_start_fires():
    src = """
    ctx = multiprocessing.get_context("fork")
    multiprocessing.set_start_method("fork")
    """
    findings = lint(src, rules=["fork-start"])
    assert rule_ids(findings) == ["fork-start", "fork-start"]


def test_fork_start_forkserver_is_clean():
    assert lint('ctx = multiprocessing.get_context("forkserver")\n',
                rules=["fork-start"]) == []


def test_all_drift_fires_on_phantom_export():
    src = """
    from fabric_tpu.crypto import der

    A = 1

    __all__ = ["A", "der", "Missing"]
    """
    findings = lint(src, path="fabric_tpu/crypto/__init__.py",
                    rules=["all-drift"])
    assert rule_ids(findings) == ["all-drift"]
    assert "Missing" in findings[0].message


def test_all_drift_guarded_import_and_non_init_are_clean():
    src = """
    try:
        from fabric_tpu.crypto import fastec
    except ImportError:
        fastec = None

    __all__ = ["fastec"]
    """
    assert lint(src, path="fabric_tpu/crypto/__init__.py",
                rules=["all-drift"]) == []
    # the rule only applies to package __init__ files
    assert lint('__all__ = ["Missing"]\n',
                path="fabric_tpu/crypto/other.py", rules=["all-drift"]) == []


def test_syntax_error_is_reported_not_raised():
    findings = lint("def broken(:\n")
    assert rule_ids(findings) == ["syntax-error"]


# ---------------------------------------------------------------------------
# suppression + exclusion
# ---------------------------------------------------------------------------


def test_per_line_suppression():
    src = (
        "try:\n"
        "    verify()\n"
        "except Exception:  # fablint: disable=broad-except  # reason\n"
        "    pass\n"
    )
    findings, suppressed = fablint.lint_source(
        src, "fabric_tpu/crypto/fixture.py", ["broad-except"]
    )
    assert findings == []
    assert suppressed == 1


def test_suppression_is_rule_specific_and_all_works():
    src = "def f(x=[]):  # fablint: disable=broad-except\n    return x\n"
    findings, suppressed = fablint.lint_source(
        src, "fabric_tpu/crypto/fixture.py", ["mutable-default"]
    )
    assert rule_ids(findings) == ["mutable-default"]  # wrong id: still fires
    src = "def f(x=[]):  # fablint: disable=all\n    return x\n"
    findings, suppressed = fablint.lint_source(
        src, "fabric_tpu/crypto/fixture.py", ["mutable-default"]
    )
    assert findings == [] and suppressed == 1


def test_generated_and_artifact_files_are_excluded(tmp_path):
    pkg = tmp_path / "fabric_tpu"
    (pkg / "protos").mkdir(parents=True)
    (pkg / "__pycache__").mkdir()
    (pkg / "native").mkdir()
    bad = "def f(x=[]):\n    return x\n"
    (pkg / "protos" / "thing_pb2.py").write_text(bad)
    (pkg / "__pycache__" / "stale.py").write_text(bad)
    (pkg / "native" / "gen.py").write_text(bad)
    (pkg / "real.py").write_text(bad)
    findings, stats = fablint.lint_paths([str(tmp_path)])
    assert stats["files"] == 1  # only real.py survives the exclusions
    assert rule_ids(findings) == ["mutable-default"]


# ---------------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------------


def test_cli_json_output_and_exit_codes(tmp_path, capsys):
    f = tmp_path / "bad.py"
    f.write_text("def f(x=[]):\n    return x\n")
    rc = fablint.main(["--json", str(f)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    assert [x["rule"] for x in out["findings"]] == ["mutable-default"]
    f.write_text("def f(x=None):\n    return x\n")
    assert fablint.main([str(f)]) == 0


def test_cli_list_rules_and_bad_rule(capsys):
    assert fablint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in fablint.RULES:
        assert rid in out
    assert len(fablint.RULES) >= 9
    assert fablint.main(["--rules", "no-such-rule", "x.py"]) == 2
    assert fablint.main([]) == 2
    assert fablint.main(["no/such/dir"]) == 2  # usage error, not a finding


# ---------------------------------------------------------------------------
# the gate invariant
# ---------------------------------------------------------------------------


def test_repo_self_check_is_clean():
    findings, stats = fablint.lint_paths([str(REPO_ROOT / "fabric_tpu")])
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message}" for f in findings
    )
    assert stats["files"] > 100  # the walk actually covered the tree


def test_toolkit_port_changed_nothing():
    """The PR 11 toolkit extraction is behavior-pinned: same chassis
    objects, same rule ids, and the repo's suppressed count exactly as
    before the port (every comment still absorbing the same finding —
    fabreg's suppression-stale rule keeps this number honest)."""
    from fabric_tpu.tools import toolkit

    assert fablint.Finding is toolkit.Finding
    assert fablint.DEFAULT_EXCLUDES == toolkit.DEFAULT_EXCLUDES
    # jit-impure left for fabtrace in PR 18 (behavior-pinned there)
    assert sorted(fablint.RULES) == [
        "all-drift", "assert-security", "broad-except", "digest-compare",
        "fork-start", "limb-dtype", "module-import",
        "mutable-default", "shell-injection",
    ]
    _findings, stats = fablint.lint_paths([str(REPO_ROOT / "fabric_tpu")])
    # 19 from the PR 11 port + the PR 13 fabcrash digest-compare
    # suppression (JSON scorecard equality, not a MAC)
    assert stats["suppressed"] == 20
