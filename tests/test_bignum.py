"""Differential tests: limb bignum vs Python arbitrary-precision ints."""

import secrets

import numpy as np
import pytest

from fabric_tpu.crypto import p256
from fabric_tpu.ops import bignum as bn


def rand_below(m, count):
    return [secrets.randbelow(m) for _ in range(count)]


@pytest.fixture(scope="module", params=[p256.P, p256.N])
def ctx(request):
    return bn.MontCtx(request.param)


def test_limb_roundtrip():
    xs = [0, 1, p256.P - 1, p256.N, 2**256 - 1] + rand_below(2**256, 5)
    arr = bn.ints_to_limbs(xs)
    assert bn.limbs_to_ints(arr) == xs
    assert arr.dtype == np.uint32
    assert (arr <= bn.LIMB_MASK).all()


def test_carry_u32():
    import jax.numpy as jnp

    # limbs deliberately far out of canonical range
    vals = np.array([[bn.LIMB_MASK * 1000, 2**31, 12345, 0]] * 2, dtype=np.uint32).T
    want = [
        sum(int(v) << (bn.LIMB_BITS * i) for i, v in enumerate(col))
        for col in vals.T
    ]
    got, carry = bn.carry_u32(jnp.asarray(vals))
    got = np.asarray(got)
    carry = np.asarray(carry)
    for j in range(vals.shape[1]):
        total = bn.limbs_to_int(got[:, j]) + (int(carry[j]) << (bn.LIMB_BITS * 4))
        assert total == want[j]


def test_mont_mul_random(ctx):
    m = ctx.m
    B = 64
    a_int = rand_below(m, B)
    b_int = rand_below(m, B)
    a = bn.ints_to_limbs(a_int)
    b = bn.ints_to_limbs(b_int)
    rinv = pow(1 << bn.RADIX_BITS, -1, m)
    got = bn.limbs_to_ints(np.asarray(bn.mont_mul(ctx, a, b)))
    want = [(x * y * rinv) % m for x, y in zip(a_int, b_int)]
    assert got == want


def test_mont_mul_edge_values(ctx):
    m = ctx.m
    edges = [0, 1, 2, m - 1, m - 2, (m - 1) // 2, bn.LIMB_MASK]
    pairs = [(x, y) for x in edges for y in edges]
    a = bn.ints_to_limbs([x for x, _ in pairs])
    b = bn.ints_to_limbs([y for _, y in pairs])
    rinv = pow(1 << bn.RADIX_BITS, -1, m)
    got = bn.limbs_to_ints(np.asarray(bn.mont_mul(ctx, a, b)))
    want = [(x * y * rinv) % m for x, y in pairs]
    assert got == want


def test_mont_mul_lax_value_bounds(ctx):
    """Inputs up to 4m (limb-canonical, value non-canonical) still reduce
    correctly with nreduce=1."""
    m = ctx.m
    vals = [4 * m - 1, 2 * m + 12345, m, 3 * m + 7]
    a = bn.ints_to_limbs(vals)
    b = bn.ints_to_limbs(list(reversed(vals)))
    rinv = pow(1 << bn.RADIX_BITS, -1, m)
    got = bn.limbs_to_ints(np.asarray(bn.mont_mul(ctx, a, b)))
    want = [(x * y * rinv) % m for x, y in zip(vals, reversed(vals))]
    assert got == want


def test_to_from_mont(ctx):
    m = ctx.m
    xs = rand_below(m, 16) + [0, 1, m - 1]
    # include values above m (e < 2^256 with m = N case)
    if m < 2**256:
        xs += [m + 1, 2**256 - 1]
    arr = bn.ints_to_limbs(xs)
    mont = bn.to_mont(ctx, arr)
    back = bn.limbs_to_ints(np.asarray(bn.from_mont(ctx, mont)))
    assert back == [x % m for x in xs]


def test_sub_mod(ctx):
    m = ctx.m
    cases = [(5, 7), (m - 1, 1), (0, m - 1), (12345, 12345)]
    a = bn.ints_to_limbs([x for x, _ in cases])
    b = bn.ints_to_limbs([y for _, y in cases])
    got = bn.limbs_to_ints(np.asarray(bn.sub_mod(ctx, a, b, b_bound=1, nreduce=1)))
    assert got == [(x - y) % m for x, y in cases]


def test_mont_pow_inverse(ctx):
    m = ctx.m
    xs = rand_below(m - 1, 8)
    xs = [x + 1 for x in xs]  # nonzero
    arr = bn.to_mont(ctx, bn.ints_to_limbs(xs))
    inv_m = bn.mont_pow(ctx, arr, m - 2)
    got = bn.limbs_to_ints(np.asarray(bn.from_mont(ctx, inv_m)))
    assert got == [pow(x, -1, m) for x in xs]


def test_mont_pow_zero(ctx):
    """0^(m-2) = 0: the infinity-Z path relies on this."""
    arr = bn.ints_to_limbs([0, 0])
    got = bn.limbs_to_ints(np.asarray(bn.mont_pow(ctx, arr, ctx.m - 2)))
    assert got == [0, 0]


def test_mont_mul_near_overflow_boundary(ctx):
    """Deliberate near-overflow regression at the proven worst-case
    interval boundary (fabflow's mechanized CIOS bound: the uint32
    lazy-carry accumulator peaks at 2684174334 (< 0.625 * 2^32) when every
    limb sits at 0x1fff).  Dense-limb operands — 19 limbs of 0x1fff —
    and operands at the documented 4m input edge (c1*c2 = 16, the
    nreduce=1 limit) are squared and chained; every step must stay
    bit-exact with the Python-int oracle.  If someone widens the radix,
    adds an accumulation term, or drops a carry, this chain wraps and
    diverges."""
    m = ctx.m
    rinv = pow(1 << bn.RADIX_BITS, -1, m)
    dense = (1 << 255) - 1  # 13-bit limbs: nineteen 0x1fff + 0xff top
    edge = 4 * m - 1        # laxest documented mont_mul input bound
    ops = [dense, edge, m - 1, dense % m]
    a = bn.ints_to_limbs(ops)
    assert (np.asarray(a)[:19, 0] == bn.LIMB_MASK).all()

    # chained squarings keep the accumulator at its densest: the oracle
    # tracks x -> x*x*R^-1 mod m exactly
    want = list(ops)
    got = a
    for _ in range(8):
        got = bn.mont_mul(ctx, got, got)
        want = [(x * x * rinv) % m for x in want]
        assert bn.limbs_to_ints(np.asarray(got)) == want

    # cross-products of the boundary operands (including 4m-edge pairs)
    pairs = [(x, y) for x in ops for y in ops]
    pa = bn.ints_to_limbs([x for x, _ in pairs])
    pb = bn.ints_to_limbs([y for _, y in pairs])
    got_p = bn.limbs_to_ints(np.asarray(bn.mont_mul(ctx, pa, pb)))
    assert got_p == [(x * y * rinv) % m for x, y in pairs]

    # the carry chain on a dense add_raw result (value = sum, no mod)
    s = bn.add_raw(bn.ints_to_limbs([dense]), bn.ints_to_limbs([dense]))
    assert bn.limbs_to_ints(np.asarray(s)) == [2 * dense]
