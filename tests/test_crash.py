"""fabcrash — crash-consistent commit plane tests.

Hand-corrupted stores through every repair/refuse rule of the
checksummed-frame recovery (torn tail, half-written header, corrupted
length prefix, checksum-valid garbage, mid-file damage), the
blockstore-ahead /
statedb-ahead recovery directions, double-recovery idempotence, the
kill action + FABRIC_TPU_CRASH_SITES grammar, the resident-table
generation stamp, and the subprocess kill canary.  The full
crash_matrix lives here slow-marked; crash_single runs in tier-1 via
tests/test_fabchaos.py's scenario sweep.
"""

import json
import os
import sqlite3
import struct
import subprocess
import sys
import zlib

import pytest

from fabric_tpu.common import faults
from fabric_tpu.ledger.blockstore import BlockStore, LedgerCorruptionError
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.protos import common_pb2, protoutil
from fabric_tpu.tools import crashchild


def make_block(number, prev_hash, payloads):
    block = protoutil.new_block(number, prev_hash)
    for p in payloads:
        block.data.data.append(p)
    return protoutil.seal_block(block)


def store_with_blocks(path, n=2):
    bs = BlockStore(path)
    prev = b""
    for i in range(n):
        b = make_block(i, prev, [b"tx-%d" % i, b"x" * 64])
        bs.add_block(b)
        prev = protoutil.block_header_hash(b.header)
    bs.close()
    return prev


def frame_offsets(path):
    """[(offset, frame_end)] of every whole frame in a chain file
    (u32 len + u32 hcrc + payload + u32 crc layout)."""
    data = open(path, "rb").read()
    out = []
    off = 0
    while off < len(data):
        (ln,) = struct.unpack_from("<I", data, off)
        end = off + 8 + ln + 4
        out.append((off, end))
        off = end
    return out


PAYLOAD_OFF = 12  # 8-byte header + a few bytes into the payload


class TestBlockStoreRecovery:
    def test_torn_partial_frame_truncated(self, tmp_path):
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 2)
        good = os.path.getsize(path)
        from fabric_tpu.ledger.blockstore import frame_header
        with open(path, "ab") as f:
            f.write(frame_header(1000) + b"partial" * 10)  # short of 1000
        bs = BlockStore(path)
        assert bs.height == 2
        assert bs.torn_tail_bytes > 0
        assert os.path.getsize(path) == good
        # and appending still works
        prev = protoutil.block_header_hash(
            bs.get_block_by_number(1).header
        )
        bs.add_block(make_block(2, prev, [b"c"]))
        assert bs.height == 3
        bs.close()

    def test_half_written_header_truncated(self, tmp_path):
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 1)
        with open(path, "ab") as f:
            f.write(b"\xff\x81")  # 2 of the 8 header bytes
        bs = BlockStore(path)
        assert bs.height == 1
        assert bs.torn_tail_bytes == 2
        bs.close()

    def test_crc_corrupt_tail_frame_truncated(self, tmp_path):
        """A checksum mismatch that reaches EOF is a torn tail: the last
        block is dropped (re-pulled by the deliver plane), never served
        damaged."""
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 2)
        frames = frame_offsets(path)
        with open(path, "r+b") as f:
            f.seek(frames[-1][0] + PAYLOAD_OFF)
            byte = f.read(1)
            f.seek(frames[-1][0] + PAYLOAD_OFF)
            f.write(bytes([byte[0] ^ 0x5A]))
        bs = BlockStore(path)
        assert bs.height == 1
        assert bs.torn_tail_bytes > 0
        bs.close()

    def test_crc_corrupt_mid_file_refuses(self, tmp_path):
        """Damage with valid bytes AFTER it cannot be one interrupted
        append: fail closed, do not silently truncate committed blocks."""
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 2)
        frames = frame_offsets(path)
        with open(path, "r+b") as f:
            f.seek(frames[0][0] + PAYLOAD_OFF)
            byte = f.read(1)
            f.seek(frames[0][0] + PAYLOAD_OFF)
            f.write(bytes([byte[0] ^ 0x5A]))
        with pytest.raises(LedgerCorruptionError):
            BlockStore(path)

    def test_corrupt_length_prefix_mid_file_refuses(self, tmp_path):
        """A flipped bit inflating a mid-file frame's LENGTH would read
        as a short frame and masquerade as a torn tail, silently
        dropping every later committed block — the header checksum is
        what catches it (review finding)."""
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 2)
        frames = frame_offsets(path)
        with open(path, "r+b") as f:
            f.seek(frames[0][0] + 1)  # inside frame 0's u32 length
            byte = f.read(1)
            f.seek(frames[0][0] + 1)
            f.write(bytes([byte[0] ^ 0x40]))  # inflate the length
        with pytest.raises(LedgerCorruptionError):
            BlockStore(path)

    def test_salvage_mode_truncates_instead(self, tmp_path, monkeypatch):
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 2)
        frames = frame_offsets(path)
        with open(path, "r+b") as f:
            f.seek(frames[0][0] + PAYLOAD_OFF)
            byte = f.read(1)
            f.seek(frames[0][0] + PAYLOAD_OFF)
            f.write(bytes([byte[0] ^ 0x5A]))
        monkeypatch.setenv("FABRIC_TPU_RECOVERY_STRICT", "0")
        bs = BlockStore(path)  # operator-forced salvage
        assert bs.height == 0
        assert os.path.getsize(path) == 0
        bs.close()

    def test_checksum_valid_garbage_refuses(self, tmp_path):
        """A frame that checksums clean but does not parse was fully
        written — that is corruption, not a torn append."""
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 1)
        from fabric_tpu.ledger.blockstore import frame_header
        garbage = b"\xff" * 24
        with open(path, "ab") as f:
            f.write(frame_header(len(garbage)))
            f.write(garbage)
            f.write(struct.pack("<I", zlib.crc32(garbage)))
        with pytest.raises(LedgerCorruptionError):
            BlockStore(path)

    def test_empty_file_opens_clean(self, tmp_path):
        path = str(tmp_path / "ch.chain")
        open(path, "wb").close()
        bs = BlockStore(path)
        assert bs.height == 0 and bs.torn_tail_bytes == 0
        bs.add_block(make_block(0, b"", [b"a"]))
        bs.close()

    def test_double_recovery_idempotent(self, tmp_path):
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 2)
        with open(path, "ab") as f:
            f.write(b"\x40" + b"torn")
        bs = BlockStore(path)
        assert bs.torn_tail_bytes > 0
        bs.close()
        repaired = open(path, "rb").read()
        bs2 = BlockStore(path)  # second recovery finds nothing to do
        assert bs2.torn_tail_bytes == 0
        assert bs2.height == 2
        bs2.close()
        assert open(path, "rb").read() == repaired

    def test_read_detects_post_open_rot(self, tmp_path):
        path = str(tmp_path / "ch.chain")
        store_with_blocks(path, 1)
        bs = BlockStore(path)
        frames = frame_offsets(path)
        with open(path, "r+b") as f:
            f.seek(frames[0][0] + PAYLOAD_OFF)
            byte = f.read(1)
            f.seek(frames[0][0] + PAYLOAD_OFF)
            f.write(bytes([byte[0] ^ 0x5A]))
        with pytest.raises(LedgerCorruptionError):
            bs.get_block_by_number(0)
        bs.close()

    def test_close_idempotent(self, tmp_path):
        bs = BlockStore(str(tmp_path / "ch.chain"))
        bs.close()
        bs.close()

    def test_failed_append_rolls_back_partial_frame(self, tmp_path):
        """An injected raise (or a real ENOSPC/fsync error) mid-append
        must not leave a partial frame for a redelivery retry to stack
        a duplicate after — strict recovery would then refuse the
        mid-file damage (review finding)."""
        path = str(tmp_path / "ch.chain")
        bs = BlockStore(path)
        b0 = make_block(0, b"", [b"a" * 64])
        bs.add_block(b0)
        good = os.path.getsize(path)
        b1 = make_block(
            1, protoutil.block_header_hash(b0.header), [b"b" * 64]
        )
        plan = faults.FaultPlan.parse("blockstore.append.post_fsync=raise:max=1")
        with faults.plan_installed(plan):
            with pytest.raises(faults.InjectedFault):
                bs.add_block(b1)
        assert bs.height == 1
        assert os.path.getsize(path) == good  # rolled back
        bs.add_block(b1)  # redelivery retry succeeds cleanly
        assert bs.height == 2
        bs.close()
        bs2 = BlockStore(path)  # and strict recovery has nothing to refuse
        assert bs2.height == 2 and bs2.torn_tail_bytes == 0
        bs2.close()


# ---------------------------------------------------------------------------
# KVLedger recovery directions (real endorsed blocks via the crash stream)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def stream(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("crashstream"))
    crashchild.build_stream(d, seed=13, n_channels=1, n_blocks=4)
    return d


def commit_all(workdir, stream_dir):
    meta, blocks, pvt = crashchild.load_stream(stream_dir)
    ledger = KVLedger(os.path.join(workdir, "ledger"), "ch0")
    for bn in range(meta["blocks"]):
        ledger.commit(blocks[0][bn], pvt_data=pvt[0].get(bn))
    return ledger


def ledger_fingerprint(ledger):
    return crashchild._digest(
        ledger,
        os.path.join(
            os.path.dirname(ledger.state_db.path), "ch0.chain"
        ),
    )


class TestKVLedgerRecovery:
    def test_blockstore_ahead_full_replay(self, tmp_path, stream):
        """Deleting the state db entirely (savepoint None) replays the
        whole chain; the derived state converges to the no-crash twin."""
        ref = commit_all(str(tmp_path / "ref"), stream)
        want = ledger_fingerprint(ref)
        ref.close()

        crashed = commit_all(str(tmp_path / "crash"), stream)
        crashed.close()
        os.remove(os.path.join(str(tmp_path / "crash"), "ledger", "ch0.state.db"))
        reopened = KVLedger(os.path.join(str(tmp_path / "crash"), "ledger"), "ch0")
        got = ledger_fingerprint(reopened)
        reopened.close()
        assert got == want

    def test_savepoint_rewind_idempotent_replay(self, tmp_path, stream):
        """Rewinding the savepoint while keeping the rows replays blocks
        over already-applied state — INSERT OR REPLACE idempotence must
        converge to the same fingerprint."""
        ref = commit_all(str(tmp_path / "ref"), stream)
        want = ledger_fingerprint(ref)
        ref.close()

        crashed = commit_all(str(tmp_path / "crash"), stream)
        crashed.close()
        db_path = os.path.join(str(tmp_path / "crash"), "ledger", "ch0.state.db")
        con = sqlite3.connect(db_path)
        con.execute("UPDATE meta SET v=? WHERE k='savepoint'", (b"0",))
        con.commit()
        con.close()
        reopened = KVLedger(os.path.join(str(tmp_path / "crash"), "ledger"), "ch0")
        got = ledger_fingerprint(reopened)
        reopened.close()
        assert got == want

    def test_statedb_ahead_refuses_then_salvages(
        self, tmp_path, stream, monkeypatch
    ):
        """A chain truncated behind the state db's back cannot be
        repaired forward: strict recovery refuses; RECOVERY_STRICT=0
        rebuilds the derived state from the surviving chain."""
        ledger = commit_all(str(tmp_path / "crash"), stream)
        ledger.close()
        chain = os.path.join(str(tmp_path / "crash"), "ledger", "ch0.chain")
        offs = frame_offsets(chain)
        with open(chain, "ab") as f:
            f.truncate(offs[1][1])  # keep blocks 0..1, state has 0..3
        with pytest.raises(LedgerCorruptionError):
            KVLedger(os.path.join(str(tmp_path / "crash"), "ledger"), "ch0")

        # the refused open must not leak handles: salvage works after
        monkeypatch.setenv("FABRIC_TPU_RECOVERY_STRICT", "0")
        salvaged = KVLedger(
            os.path.join(str(tmp_path / "crash"), "ledger"), "ch0"
        )
        monkeypatch.delenv("FABRIC_TPU_RECOVERY_STRICT")
        got = ledger_fingerprint(salvaged)
        salvaged.close()

        # reference twin that only ever committed 2 blocks
        meta, blocks, pvt = crashchild.load_stream(stream)
        ref = KVLedger(os.path.join(str(tmp_path / "ref2"), "ledger"), "ch0")
        for bn in range(2):
            ref.commit(blocks[0][bn], pvt_data=pvt[0].get(bn))
        want = ledger_fingerprint(ref)
        ref.close()
        # the pvt store retained records above the salvage point, so the
        # file digests legitimately differ; state/chain/masks must match
        for key in ("height", "commit_hash", "chain_sha", "masks_sha",
                    "state_sha", "hashed_sha", "savepoint"):
            assert got[key] == want[key], key

    def test_pvt_tail_lost_records_missing_markers(self, tmp_path, stream):
        """A torn pvt tail whose block survived: recovery truncates the
        record and registers missing-data markers so the reconciler can
        re-fetch — the store never stays silently behind the chain."""
        ledger = commit_all(str(tmp_path / "crash"), stream)
        ledger.close()
        pvt_path = os.path.join(
            str(tmp_path / "crash"), "ledger", "ch0.pvtdata"
        )
        size = os.path.getsize(pvt_path)
        with open(pvt_path, "ab") as f:
            f.truncate(size - 3)  # tear the last record
        reopened = KVLedger(os.path.join(str(tmp_path / "crash"), "ledger"), "ch0")
        assert reopened.pvt_store.last_committed_block == 3
        missing = reopened.pvt_store.get_missing_pvt_data()
        assert 3 in missing and missing[3][0].collection == "secret"
        # the on-block hashed writes were never lost
        assert reopened.height == 4
        reopened.close()

    def test_close_idempotent(self, tmp_path, stream):
        ledger = commit_all(str(tmp_path / "w"), stream)
        ledger.close()
        ledger.close()

    def test_store_ctor_refusal_closes_earlier_stores(
        self, tmp_path, stream, monkeypatch
    ):
        """A refusal raised from the pvt store CONSTRUCTOR (not
        _recover) must still close the already-open block store, so the
        documented retry-with-RECOVERY_STRICT=0 workflow works (review
        finding)."""
        ledger = commit_all(str(tmp_path / "crash"), stream)
        ledger.close()
        pvt_path = os.path.join(
            str(tmp_path / "crash"), "ledger", "ch0.pvtdata"
        )
        offs = []
        data = open(pvt_path, "rb").read()
        off = 0
        while off < len(data):
            (ln,) = struct.unpack_from("<I", data, off)
            offs.append(off)
            off += 8 + ln + 4
        with open(pvt_path, "r+b") as f:
            f.seek(offs[0] + 12)  # payload of the FIRST record
            byte = f.read(1)
            f.seek(offs[0] + 12)
            f.write(bytes([byte[0] ^ 0x5A]))
        with pytest.raises(LedgerCorruptionError):
            KVLedger(os.path.join(str(tmp_path / "crash"), "ledger"), "ch0")
        monkeypatch.setenv("FABRIC_TPU_RECOVERY_STRICT", "0")
        salvaged = KVLedger(
            os.path.join(str(tmp_path / "crash"), "ledger"), "ch0"
        )
        assert salvaged.height == 4  # chain untouched by the pvt salvage
        salvaged.close()

    def test_nonpersistent_rebuild_carries_generation(self, tmp_path, stream):
        meta, blocks, pvt = crashchild.load_stream(stream)
        ledger = KVLedger(
            os.path.join(str(tmp_path / "w"), "ledger"), "ch0",
            persistent=False,
        )
        ledger.commit(blocks[0][0], pvt_data=pvt[0].get(0))
        g0 = ledger.state_db.state_generation
        ledger.rebuild_dbs()
        assert ledger.state_db.state_generation > g0
        ledger.close()

    def test_snapshot_bootstrapped_pvt_gap_skips_missing_blocks(
        self, tmp_path
    ):
        """The pvt-gap pre-loop must not dereference pre-snapshot blocks
        the store does not hold (review finding): a bootstrapped ledger
        whose pvt store is behind opens cleanly instead of crashing."""
        from fabric_tpu.ledger.persistent import SqliteVersionedDB

        ledger_dir = str(tmp_path / "ledger")
        bs = BlockStore.bootstrap_from_snapshot(
            os.path.join(ledger_dir, "ch0.chain"), height=2,
            last_hash=b"\x01" * 32,
        )
        bs.close()
        # state restored from the snapshot up to block 1; pvt store empty
        db = SqliteVersionedDB(os.path.join(ledger_dir, "ch0.state.db"))
        db.commit_block(
            __import__(
                "fabric_tpu.ledger.statedb", fromlist=["UpdateBatch"]
            ).UpdateBatch(),
            savepoint=1,
        )
        db.close()
        ledger = KVLedger(ledger_dir, "ch0")
        assert ledger.height == 2
        ledger.close()


# ---------------------------------------------------------------------------
# kill action + crash-sites grammar
# ---------------------------------------------------------------------------


class TestKillAction:
    def test_parse_kill_with_at(self):
        plan = faults.FaultPlan.parse("a.b=kill:at=3:max=1")
        (spec,) = plan.specs()
        assert spec.action == "kill" and spec.at_key == 3 and spec.max_fires == 1

    def test_at_key_gates_any_action(self):
        plan = faults.FaultPlan.parse("a.b=raise:at=3")
        assert plan.check("a.b", key=2) is None
        assert plan.check("a.b", key=None) is None
        assert plan.check("a.b", key=3).action == "raise"

    def test_crash_specs_from_text(self):
        specs = faults.crash_specs_from_text(
            "blockstore.append.pre_fsync@3; kvledger.commit.pre_pvt"
        )
        assert [s.site for s in specs] == [
            "blockstore.append.pre_fsync", "kvledger.commit.pre_pvt",
        ]
        assert specs[0].at_key == 3 and specs[1].at_key is None
        assert all(s.action == "kill" and s.max_fires == 1 for s in specs)

    def test_crash_specs_malformed_raises(self):
        with pytest.raises(ValueError):
            faults.crash_specs_from_text("@3")

    def test_kill_exits_with_sigkill_code(self):
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                "from fabric_tpu.common import faults\n"
                "faults.install_plan(faults.FaultPlan.parse('x=kill'))\n"
                "faults.fault_point('x')\n"
                "raise SystemExit(99)  # unreachable\n",
            ],
            capture_output=True,
            timeout=60,
        )
        assert r.returncode == faults.KILL_EXIT_CODE

    def test_env_crash_sites_merge_with_faults_plan(self):
        r = subprocess.run(
            [sys.executable, "-c",
             "from fabric_tpu.common import faults\n"
             "plan = faults.active_plan()\n"
             "sites = sorted(s.site for s in plan.specs())\n"
             "print(sites)\n"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ,
                 "FABRIC_TPU_FAULTS": "deliver.pull=raise:0.5",
                 "FABRIC_TPU_CRASH_SITES": "kvledger.commit.pre_pvt@2"},
        )
        assert r.returncode == 0, r.stderr
        assert "deliver.pull" in r.stdout
        assert "kvledger.commit.pre_pvt" in r.stdout


# ---------------------------------------------------------------------------
# resident-table generation stamp
# ---------------------------------------------------------------------------


class TestGenerationStamp:
    def test_sqlite_clear_bumps_generation(self, tmp_path):
        from fabric_tpu.ledger.persistent import SqliteVersionedDB

        db = SqliteVersionedDB(str(tmp_path / "s.db"))
        g0 = db.state_generation
        db.clear()
        assert db.state_generation == g0 + 1
        db.close()
        db.close()  # idempotent

    def test_kvledger_rebuild_bumps_generation(self, tmp_path, stream):
        ledger = commit_all(str(tmp_path / "w"), stream)
        g0 = ledger.state_db.state_generation
        ledger.rebuild_dbs()
        assert ledger.state_db.state_generation > g0
        ledger.close()

    def test_out_of_band_mutation_invalidates_resident_table(self):
        jax = pytest.importorskip("jax")  # noqa: F841
        from fabric_tpu.ledger import rwset as rw
        from fabric_tpu.ledger.mvcc import Validator
        from fabric_tpu.ledger.mvcc_device import ResidentDeviceValidator
        from fabric_tpu.ledger.statedb import UpdateBatch, VersionedDB

        VALID = __import__(
            "fabric_tpu.common.txflags", fromlist=["TxValidationCode"]
        ).TxValidationCode.VALID
        db = VersionedDB()
        seed = UpdateBatch()
        seed.put("cc", "k0", b"seed", rw.Version(0, 0))
        db.apply_updates(seed)
        res = ResidentDeviceValidator(db, capacity=16)

        b1 = [rw.TxRwSet((rw.NsRwSet(
            "cc", (rw.KVRead("k0", rw.Version(0, 0)),),
            (rw.KVWrite("k0", False, b"v1"),),
        ),))]
        codes, up, hup = res.validate_and_prepare_batch(1, b1, [VALID])
        assert res.last_path == "device" and codes == [VALID]
        db.apply_updates(up, hup)

        # behind-the-back rollback + re-commit
        ob = UpdateBatch()
        ob.put("cc", "k0", b"rolled", rw.Version(0, 7))
        db.apply_updates(ob)
        db.bump_generation()

        # a read claiming the table's (now dead) version must conflict,
        # and one claiming the live version must pass
        b2 = [
            rw.TxRwSet((rw.NsRwSet(
                "cc", (rw.KVRead("k0", rw.Version(1, 0)),), ()),)),
            rw.TxRwSet((rw.NsRwSet(
                "cc", (rw.KVRead("k0", rw.Version(0, 7)),), ()),)),
        ]
        codes2, _u, _h = res.validate_and_prepare_batch(
            2, b2, [VALID, VALID]
        )
        host = Validator(db).validate_and_prepare_batch(
            2, b2, [VALID, VALID]
        )[0]
        assert codes2 == host
        assert res.invalidations == 1
        assert res.last_path == "device"  # rebuilt table, live generation


# ---------------------------------------------------------------------------
# the full kill-point matrix (slow; crash_single is the tier-1 canary)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_crash_matrix_every_site_converges():
    from fabric_tpu.tools.fabchaos import SCENARIOS, StageClock

    det, obs = SCENARIOS["crash_matrix"](7, StageClock(), 1.0)
    assert all(
        entry["converged"] and entry["killed"]
        for entry in det["sites"].values()
    )
    assert len(det["sites"]) == 7
