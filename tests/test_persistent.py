"""Persistent state/history (sqlite stateleveldb analog) + rich selector
queries (statecouchdb analog)."""

import json

import pytest

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.persistent import SqliteVersionedDB
from fabric_tpu.ledger.queries import QueryError, execute, matches
from fabric_tpu.ledger.rwset import Version
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.ledger.statedb import (
    HashedUpdateBatch,
    PvtUpdateBatch,
    UpdateBatch,
    VersionedDB,
)
from fabric_tpu.protos import protoutil


def make_block(number, prev_hash, payloads):
    block = protoutil.new_block(number, prev_hash)
    for p in payloads:
        block.data.data.append(p)
    return protoutil.seal_block(block)


def write_rwset(ns, items):
    return rw.TxRwSet(
        (
            rw.NsRwSet(
                ns, (), tuple(rw.KVWrite(k, v is None, v or b"") for k, v in items)
            ),
        )
    )


# ----------------------------------------------------------------------
# SqliteVersionedDB vs in-memory VersionedDB parity
# ----------------------------------------------------------------------


def _fill(db):
    batch = UpdateBatch()
    batch.put("ns1", "a", b"1", Version(0, 0))
    batch.put("ns1", "b", b"2", Version(0, 1), metadata=b"md")
    batch.put("ns1", "béta", b"3", Version(0, 2))
    batch.put("ns2", "z", b"4", Version(0, 3))
    hashed = HashedUpdateBatch()
    hashed.put("ns1", "coll", b"\x01\x02", b"vh", Version(0, 1), metadata=b"hm")
    pvt = PvtUpdateBatch()
    pvt.put("ns1", "coll", "pk", b"pv", Version(0, 1))
    db.apply_updates(batch, hashed, pvt)


@pytest.mark.parametrize("factory", [VersionedDB, "sqlite"])
def test_db_parity(factory, tmp_path):
    db = (
        SqliteVersionedDB(str(tmp_path / "s.db"))
        if factory == "sqlite"
        else factory()
    )
    _fill(db)
    assert db.get_state("ns1", "a").value == b"1"
    assert db.get_state("ns1", "b").metadata == b"md"
    assert db.get_state("ns1", "nope") is None
    assert db.get_version("ns1", "b") == Version(0, 1)
    assert db.get_hashed_state("ns1", "coll", b"\x01\x02").value == b"vh"
    assert db.get_hashed_metadata("ns1", "coll", b"\x01\x02") == b"hm"
    assert db.get_private_data("ns1", "coll", "pk").value == b"pv"
    assert db.num_keys() == 4
    scan = [(k, vv.value) for k, vv in db.get_state_range("ns1", "a", "c", False)]
    assert scan == [("a", b"1"), ("b", b"2"), ("béta", b"3")]
    scan = [(k, vv.value) for k, vv in db.get_state_range("ns1", "b", "", False)]
    assert [k for k, _ in scan] == ["b", "béta"]
    assert [x[0:2] for x in db.iter_all_state()] == [
        ("ns1", "a"),
        ("ns1", "b"),
        ("ns1", "béta"),
        ("ns2", "z"),
    ]
    # deletes
    batch = UpdateBatch()
    batch.delete("ns1", "a", Version(1, 0))
    db.apply_updates(batch)
    assert db.get_state("ns1", "a") is None
    assert db.num_keys() == 3


def test_sqlite_persists_across_reopen(tmp_path):
    path = str(tmp_path / "s.db")
    db = SqliteVersionedDB(path)
    _fill(db)
    db.commit_block(UpdateBatch(), savepoint=7, commit_hash=b"\xaa" * 32)
    db.close()
    db2 = SqliteVersionedDB(path)
    assert db2.get_state("ns1", "béta").value == b"3"
    assert db2.savepoint() == 7
    assert db2.commit_hash() == b"\xaa" * 32


# ----------------------------------------------------------------------
# KVLedger: restart without replay
# ----------------------------------------------------------------------


def test_kvledger_restart_uses_savepoint_not_replay(tmp_path, monkeypatch):
    ledger = KVLedger(str(tmp_path), "ch")
    prev = b""
    for n in range(5):
        block = make_block(n, prev, [b"opaque-envelope"])
        ledger.commit(block, rwsets=[write_rwset("cc", [(f"k{n}", b"v%d" % n)])])
        prev = protoutil.block_header_hash(block.header)
    saved_hash = ledger.commit_hash
    assert ledger.get_state("cc", "k4") == b"v4"
    assert ledger.get_history_for_key("cc", "k3") == [Version(3, 0)]
    ledger.block_store.close()
    ledger.pvt_store.close()
    ledger.state_db.close()

    replays = []
    monkeypatch.setattr(
        KVLedger,
        "_apply_committed_block",
        lambda self, block: replays.append(block.header.number),
    )
    again = KVLedger(str(tmp_path), "ch")
    # all 5 blocks were under the savepoint: recovery replayed NOTHING
    assert replays == []
    assert again.height == 5
    assert again.get_state("cc", "k2") == b"v2"
    assert again.commit_hash == saved_hash
    assert again.get_history_for_key("cc", "k1") == [Version(1, 0)]


def test_kvledger_replays_only_tail_after_partial_commit(tmp_path, monkeypatch):
    """A block in the store but past the savepoint (crash between block
    append and state write) is replayed on reopen — and only it."""
    ledger = KVLedger(str(tmp_path), "ch")
    b0 = make_block(0, b"", [b"x"])
    ledger.commit(b0, rwsets=[write_rwset("cc", [("k0", b"v0")])])
    # simulate the crash window: append block 1 to the store only
    b1 = make_block(1, protoutil.block_header_hash(b0.header), [b"y"])
    protoutil.init_block_metadata(b1)
    ledger.block_store.add_block(b1)
    ledger.block_store.close()
    ledger.pvt_store.close()
    ledger.state_db.close()

    replays = []
    orig = KVLedger._apply_committed_block
    monkeypatch.setattr(
        KVLedger,
        "_apply_committed_block",
        lambda self, block: (replays.append(block.header.number), orig(self, block)),
    )
    again = KVLedger(str(tmp_path), "ch")
    assert replays == [1]
    assert again.get_state("cc", "k0") == b"v0"


# ----------------------------------------------------------------------
# rich queries
# ----------------------------------------------------------------------

MARBLES = [
    ("m1", {"docType": "marble", "color": "red", "size": 5, "owner": "tom"}),
    ("m2", {"docType": "marble", "color": "blue", "size": 10, "owner": "jerry"}),
    ("m3", {"docType": "marble", "color": "red", "size": 25, "owner": "tom"}),
    ("m4", {"docType": "pebble", "color": "red", "size": 5, "owner": "anna"}),
    ("m5", {"docType": "marble", "color": "green", "size": 50, "owner": "anna",
            "tags": ["shiny", "rare"]}),
]


def _query_db(db_kind, tmp_path):
    db = (
        SqliteVersionedDB(str(tmp_path / "q.db"))
        if db_kind == "sqlite"
        else VersionedDB()
    )
    batch = UpdateBatch()
    for i, (key, doc) in enumerate(MARBLES):
        batch.put("marbles", key, json.dumps(doc).encode(), Version(0, i))
    batch.put("marbles", "raw", b"\x00not-json", Version(0, 9))
    db.apply_updates(batch)
    return db


@pytest.mark.parametrize("db_kind", ["mem", "sqlite"])
def test_rich_query_selectors(db_kind, tmp_path):
    db = _query_db(db_kind, tmp_path)

    def q(sel, **kw):
        return [k for k, _ in db.execute_query("marbles", {"selector": sel, **kw})]

    assert q({"color": "red"}) == ["m1", "m3", "m4"]
    assert q({"docType": "marble", "color": "red"}) == ["m1", "m3"]
    assert q({"size": {"$gt": 5, "$lte": 25}}) == ["m2", "m3"]
    assert q({"owner": {"$in": ["tom", "anna"]}}) == ["m1", "m3", "m4", "m5"]
    assert q({"$or": [{"color": "blue"}, {"size": 50}]}) == ["m2", "m5"]
    assert q({"$not": {"docType": "marble"}}) == ["m4"]
    assert q({"tags": {"$elemMatch": {"$eq": "rare"}}}) == ["m5"]
    assert q({"tags": {"$exists": True}}) == ["m5"]
    assert q({"owner": {"$regex": "^t"}}) == ["m1", "m3"]
    assert q({"color": "red"}, limit=2) == ["m1", "m3"]
    assert q({"color": "red"}, skip=1) == ["m3", "m4"]
    assert q({"docType": "marble"}, sort=[{"size": "desc"}]) == [
        "m5", "m3", "m2", "m1",
    ]
    # projection
    rows = db.execute_query(
        "marbles", {"selector": {"color": "blue"}, "fields": ["owner"]}
    )
    assert rows == [("m2", b'{"owner": "jerry"}')]
    # non-JSON rows never match
    assert q({}) == ["m1", "m2", "m3", "m4", "m5"]


def test_query_errors():
    with pytest.raises(QueryError):
        execute([], {"no_selector": {}})
    with pytest.raises(QueryError):
        execute([("k", b"{}")], {"selector": {"$bogus": []}})
    with pytest.raises(QueryError):
        matches({"f": {"$unknown": 1}}, {"f": 1})


def test_simulator_rich_query_records_no_reads(tmp_path):
    db = _query_db("mem", tmp_path)
    sim = TxSimulator(db, "tx1")
    rows = sim.execute_query("marbles", json.dumps({"selector": {"owner": "tom"}}))
    assert [k for k, _ in rows] == ["m1", "m3"]
    res = sim.get_tx_simulation_results()
    pub = res.rwset
    # rich queries are not phantom-protected: empty read set
    assert all(not ns.reads and not ns.range_queries for ns in pub.ns_rw_sets)
