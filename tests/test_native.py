"""C++ host runtime (native/fabric_native.cc via ctypes): batched
SHA-256 and strict-DER signature parsing, differential against the
pure-Python implementations (which are also the fallback path)."""

import hashlib
import secrets

import numpy as np
import pytest

from fabric_tpu.crypto import der, p256
from fabric_tpu.utils import native


def test_native_library_builds_and_loads():
    # the toolchain is part of the environment contract; if this fails
    # the fallbacks still work but we want to know
    assert native.available()


def test_batch_sha256_differential():
    msgs = [secrets.token_bytes(n) for n in (0, 1, 31, 55, 56, 63, 64, 65, 1000, 10000)]
    got = native.batch_sha256(msgs)
    assert got.shape == (len(msgs), 32)
    for m, g in zip(msgs, got):
        assert bytes(g) == hashlib.sha256(m).digest()
    assert native.batch_sha256([]).shape == (0, 32)


def test_batch_der_parse_valid_signatures():
    sigs, want = [], []
    for _ in range(100):
        r = secrets.randbelow(p256.N - 1) + 1
        s = secrets.randbelow(p256.N - 1) + 1
        sigs.append(der.marshal_signature(r, s))
        want.append((r, s, p256.is_low_s(s)))
    r_arr, s_arr, ok, low = native.batch_der_parse(sigs)
    for i, (r, s, lows) in enumerate(want):
        assert ok[i] == 1
        assert int.from_bytes(bytes(r_arr[i]), "big") == r
        assert int.from_bytes(bytes(s_arr[i]), "big") == s
        assert bool(low[i]) == lows


@pytest.mark.parametrize(
    "bad",
    [
        b"",
        b"\x30\x02\x02\x00",
        b"\xff" * 16,
        der.marshal_signature(5, 7)[:-1],  # truncated
        # non-minimal integer: leading zero before a low byte
        b"\x30\x08\x02\x02\x00\x05\x02\x02\x00\x07",
    ],
)
def test_batch_der_parse_rejects_malformed(bad):
    _, _, ok, _ = native.batch_der_parse([bad])
    assert ok[0] == 0


def test_batch_der_parse_tolerates_trailing_bytes():
    """The Go asn1 quirk der.py documents: extra bytes after the SEQUENCE
    are tolerated. BOTH parsers must accept, or peers diverge."""
    sig = der.marshal_signature(5, 7) + b"\x00\xff"
    _, _, ok, _ = native.batch_der_parse([sig])
    assert ok[0] == 1
    assert der.unmarshal_signature(sig) == (5, 7)


def test_der_fuzz_native_matches_python():
    """Random valid signatures with random byte mutations: the native
    parser's accept/reject + values must equal the Python reference."""
    import random

    rng = random.Random(1234)
    cases = []
    for _ in range(400):
        r = rng.randrange(1, p256.N)
        s = rng.randrange(1, p256.N)
        sig = bytearray(der.marshal_signature(r, s))
        mutations = rng.randrange(0, 3)
        for _ in range(mutations):
            kind = rng.randrange(3)
            if kind == 0 and sig:
                sig[rng.randrange(len(sig))] = rng.randrange(256)
            elif kind == 1:
                sig = sig[: rng.randrange(len(sig) + 1)]
            else:
                sig += bytes([rng.randrange(256)])
        cases.append(bytes(sig))

    r_arr, s_arr, ok, _ = native.batch_der_parse(cases)
    for i, sig in enumerate(cases):
        try:
            rr, ss = der.unmarshal_signature(sig)
            py_ok = 1 <= rr < p256.N and 1 <= ss < p256.N
        except der.DerError:
            py_ok = False
            rr = ss = None
        assert bool(ok[i]) == py_ok, (i, sig.hex())
        if py_ok:
            assert int.from_bytes(bytes(r_arr[i]), "big") == rr, sig.hex()
            assert int.from_bytes(bytes(s_arr[i]), "big") == ss, sig.hex()


def test_batch_der_parse_rejects_out_of_range():
    zero_s = der.marshal_signature(5, p256.N)  # s == n
    _, _, ok, _ = native.batch_der_parse([zero_s])
    assert ok[0] == 0


def test_der_parse_matches_python_fallback():
    """The C++ parser and the Python fallback must agree bit-for-bit on a
    mixed batch (the fallback is what runs without the toolchain)."""
    sigs = []
    for i in range(50):
        r = secrets.randbelow(p256.N - 1) + 1
        s = secrets.randbelow(p256.N - 1) + 1
        sigs.append(der.marshal_signature(r, s))
    sigs += [b"", b"\x30\x01\x00", secrets.token_bytes(20)]

    native_out = native.batch_der_parse(sigs)

    # force the fallback by simulating a missing library
    saved = native._lib, native._tried
    native._lib, native._tried = None, True
    try:
        fallback_out = native.batch_der_parse(sigs)
    finally:
        native._lib, native._tried = saved

    for a, b in zip(native_out, fallback_out):
        assert np.array_equal(a, b)
