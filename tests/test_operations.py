"""Operations plane: metrics SPI, flogging level specs, ops HTTP server
(reference core/operations/system.go, common/flogging, common/metrics)."""

import json
import urllib.request

import pytest

from conftest import requires_crypto

from fabric_tpu.common import flogging
from fabric_tpu.common.metrics import (
    CounterOpts,
    DisabledProvider,
    GaugeOpts,
    HistogramOpts,
    PrometheusProvider,
    StatsdProvider,
)
from fabric_tpu.operations import Options, System


# ---------------- flogging ----------------


def test_flogging_spec_roundtrip():
    flogging.activate_spec("gossip=warn:ledger.state=debug:info")
    assert flogging.spec() == "gossip=warn:ledger.state=debug:info"
    flogging.reset()
    assert flogging.spec() == "info"


def test_flogging_levels_apply_to_subtrees():
    flogging.activate_spec("gossip=error:debug")
    import logging

    assert flogging.must_get_logger("gossip").level == logging.ERROR
    assert flogging.must_get_logger("gossip.state").level == logging.ERROR
    assert flogging.must_get_logger("ledger").level == logging.DEBUG
    flogging.reset()


def test_flogging_invalid_spec_rejected():
    with pytest.raises(flogging.InvalidSpecError):
        flogging.activate_spec("gossip=notalevel")
    with pytest.raises(flogging.InvalidSpecError):
        flogging.activate_spec("=debug")


# ---------------- metrics ----------------


def test_prometheus_counter_and_gauge():
    p = PrometheusProvider()
    c = p.new_counter(
        CounterOpts(
            namespace="ledger",
            name="transaction_count",
            help="tx count",
            label_names=("channel", "validation_code"),
        )
    )
    c.with_labels("channel", "ch1", "validation_code", "VALID").add()
    c.with_labels("channel", "ch1", "validation_code", "VALID").add(2)
    c.with_labels("channel", "ch1", "validation_code", "MVCC_READ_CONFLICT").add()
    g = p.new_gauge(GaugeOpts(namespace="gossip", name="peers_known"))
    g.set(4)
    text = p.gather()
    assert (
        'ledger_transaction_count{channel="ch1",validation_code="VALID"} 3'
        in text
    )
    assert "# TYPE ledger_transaction_count counter" in text
    assert "gossip_peers_known 4" in text


def test_prometheus_histogram_buckets():
    p = PrometheusProvider()
    h = p.new_histogram(
        HistogramOpts(
            namespace="ledger",
            name="block_processing_time",
            buckets=(0.1, 1.0, 10.0),
        )
    )
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    text = p.gather()
    assert 'ledger_block_processing_time_bucket{le="0.1"} 1' in text
    assert 'ledger_block_processing_time_bucket{le="1"} 3' in text
    assert 'ledger_block_processing_time_bucket{le="10"} 4' in text
    assert 'ledger_block_processing_time_bucket{le="+Inf"} 5' in text
    assert "ledger_block_processing_time_count 5" in text


def test_prometheus_rejects_kind_mismatch():
    p = PrometheusProvider()
    p.new_counter(CounterOpts(name="x"))
    with pytest.raises(ValueError):
        p.new_gauge(GaugeOpts(name="x"))


def test_statsd_provider_formats_buckets():
    lines = []
    p = StatsdProvider(lines.append, prefix="peer0")
    c = p.new_counter(
        CounterOpts(
            namespace="ledger",
            name="tx_count",
            label_names=("channel",),
            statsd_format="%{#fqname}.%{channel}",
        )
    )
    c.with_labels("channel", "ch1").add()
    assert lines == ["peer0.ledger.tx.count.ch1:1|c"]


def test_disabled_provider_noops():
    p = DisabledProvider()
    p.new_counter(CounterOpts(name="c")).add()
    p.new_gauge(GaugeOpts(name="g")).set(1)
    p.new_histogram(HistogramOpts(name="h")).observe(1)


def test_disabled_provider_labeled_children_stay_disabled():
    """Regression (PR 10): the old DisabledProvider patched no-ops onto
    the parent INSTANCE only, so ``with_labels()`` returned a live
    base-class metric that silently recorded and accumulated series
    memory.  Now every labeled child IS the no-op (with_labels returns
    self) and there is no backing series dict at all."""
    p = DisabledProvider()
    c = p.new_counter(
        CounterOpts(name="c", label_names=("channel",))
    )
    labeled = c.with_labels("channel", "ch1")
    assert labeled is c  # the no-op hands back itself
    labeled.add(5)
    labeled.add(5)
    # no _Metric behind a disabled instrument: nothing can accumulate
    assert not hasattr(labeled, "_m")
    g = p.new_gauge(GaugeOpts(name="g", label_names=("x",)))
    assert g.with_labels("x", "1") is g
    g.with_labels("x", "1").set(3)
    g.with_labels("x", "1").add(2)
    assert not hasattr(g, "_m")
    h = p.new_histogram(HistogramOpts(name="h", label_names=("x",)))
    assert h.with_labels("x", "1") is h
    h.with_labels("x", "1").observe(0.5)
    assert not hasattr(h, "_m")


def test_statsd_with_labels_validates_without_registry_allocation():
    """Label validation is the shared ``validate_label_values`` now —
    the statsd path used to build a throwaway ``_Metric`` per
    with_labels call just to run it.  Semantics must be unchanged:
    missing/odd labels still raise ValueError."""
    lines = []
    p = StatsdProvider(lines.append)
    c = p.new_counter(
        CounterOpts(
            name="tx", label_names=("channel",),
            statsd_format="%{#fqname}.%{channel}",
        )
    )
    with pytest.raises(ValueError, match="missing label values"):
        c.with_labels("wrong_name", "x")
    with pytest.raises(ValueError, match="name/value pairs"):
        c.with_labels("channel")
    c.with_labels("channel", "ch9").add()
    assert lines == ["tx.ch9:1|c"]


# ---------------- operations server ----------------


@pytest.fixture
def ops_system():
    system = System(Options(listen_address="127.0.0.1:0"))
    system.start()
    yield system
    system.stop()
    flogging.reset()


def _get(system, path):
    return urllib.request.urlopen(f"http://{system.addr}{path}")


def test_ops_stop_reaps_serve_thread():
    # regression (fablife thread-unjoined): stop() relied on
    # shutdown() settling serve_forever but never reaped the thread;
    # the join is now explicit and the handle cleared
    system = System(Options(listen_address="127.0.0.1:0"))
    system.start()
    t = system._thread
    assert t is not None and t.is_alive()
    system.stop()
    assert not t.is_alive(), "stop() must join the serve thread"
    assert system._thread is None
    flogging.reset()


def test_ops_version_and_metrics(ops_system):
    with _get(ops_system, "/version") as resp:
        assert json.load(resp)["Version"]
    ops_system.provider.new_counter(CounterOpts(name="up")).add()
    with _get(ops_system, "/metrics") as resp:
        assert b"up 1" in resp.read()


def test_ops_healthz(ops_system):
    with _get(ops_system, "/healthz") as resp:
        assert json.load(resp)["status"] == "OK"

    def failing():
        raise RuntimeError("couchdb down")

    ops_system.register_checker("statedb", failing)
    try:
        _get(ops_system, "/healthz")
        assert False, "expected 503"
    except urllib.error.HTTPError as err:
        payload = json.load(err)
        assert payload["failed_checks"] == [
            {"component": "statedb", "reason": "couchdb down"}
        ]


def test_ops_logspec_get_and_put(ops_system):
    req = urllib.request.Request(
        f"http://{ops_system.addr}/logspec",
        data=json.dumps({"spec": "gossip=debug:warn"}).encode(),
        method="PUT",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 204
    with _get(ops_system, "/logspec") as resp:
        assert json.load(resp)["spec"] == "gossip=debug:warn"

    bad = urllib.request.Request(
        f"http://{ops_system.addr}/logspec",
        data=json.dumps({"spec": "nope=nope"}).encode(),
        method="PUT",
    )
    try:
        urllib.request.urlopen(bad)
        assert False, "expected 400"
    except urllib.error.HTTPError as err:
        assert err.code == 400


def test_ops_healthz_names_every_failed_checker(ops_system):
    """503 must carry ALL failing components, sorted, with reasons —
    and a deregistered checker must stop failing the probe."""

    def db_down():
        raise RuntimeError("couchdb down")

    def pool_cold():
        raise RuntimeError("pool in cooldown")

    ops_system.register_checker("statedb", db_down)
    ops_system.register_checker("ec-pool", pool_cold)
    ops_system.register_checker("healthy", lambda: None)
    try:
        _get(ops_system, "/healthz")
        assert False, "expected 503"
    except urllib.error.HTTPError as err:
        assert err.code == 503
        payload = json.load(err)
        assert payload["status"] == "Service Unavailable"
        assert payload["failed_checks"] == [
            {"component": "ec-pool", "reason": "pool in cooldown"},
            {"component": "statedb", "reason": "couchdb down"},
        ]
    ops_system.deregister_checker("statedb")
    ops_system.deregister_checker("ec-pool")
    with _get(ops_system, "/healthz") as resp:
        assert json.load(resp)["status"] == "OK"


def test_ops_logspec_malformed_body_is_400_and_spec_unchanged(ops_system):
    flogging.activate_spec("gossip=warn:info")
    for body in (b"{not json", b'{"spec": ["not", "a", "string"]}'):
        req = urllib.request.Request(
            f"http://{ops_system.addr}/logspec", data=body, method="PUT",
        )
        try:
            urllib.request.urlopen(req)
            assert False, "expected 400"
        except urllib.error.HTTPError as err:
            assert err.code == 400
            assert "error" in json.load(err)
        # the active spec survives every malformed PUT
        with _get(ops_system, "/logspec") as resp:
            assert json.load(resp)["spec"] == "gossip=warn:info"


def test_ops_metrics_concurrent_scrapes_under_write_load(ops_system):
    """/metrics scraped from several threads while a writer hammers the
    provider: every scrape parses, no exceptions, monotonically growing
    counter values (the gather path locks per family)."""
    import re
    import threading

    c = ops_system.provider.new_counter(
        CounterOpts(name="load_counter", label_names=("lane",))
    )
    h = ops_system.provider.new_histogram(
        HistogramOpts(name="load_hist", buckets=(0.1, 1.0))
    )
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            c.with_labels("lane", str(i % 4)).add()
            h.observe(0.05 * (i % 30))
            i += 1

    errors = []
    seen = []

    def scraper():
        try:
            for _ in range(20):
                with _get(ops_system, "/metrics") as resp:
                    text = resp.read().decode()
                vals = [
                    int(m)
                    for m in re.findall(r'load_counter\{lane="0"\} (\d+)', text)
                ]
                if vals:
                    seen.append(vals[0])
                assert "# TYPE load_hist histogram" in text
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append(exc)

    w = threading.Thread(target=writer)
    scrapers = [threading.Thread(target=scraper) for _ in range(3)]
    w.start()
    for t in scrapers:
        t.start()
    for t in scrapers:
        t.join()
    stop.set()
    w.join()
    assert errors == []
    # every scrape observed a parseable, non-torn exposition; values are
    # sane (non-negative ints parsed out of a consistent line format)
    assert seen and all(v >= 0 for v in seen)


def test_ops_system_serves_injected_provider():
    """Options.provider (PR 10): a System can mount an already-live
    provider — how the sidecar and node shells expose the fabobs
    data-plane registry on /metrics."""
    from fabric_tpu.common.metrics import PrometheusProvider as PP

    provider = PP()
    provider.new_counter(CounterOpts(name="preexisting")).add(7)
    system = System(
        Options(listen_address="127.0.0.1:0", provider=provider)
    )
    system.start()
    try:
        assert system.provider is provider
        with _get(system, "/metrics") as resp:
            assert b"preexisting 7" in resp.read()
    finally:
        system.stop()


# ---------------- operations TLS (core/operations/system.go TLS) ----------


def _self_signed(tmp_path, name):
    """Self-signed cert + key PEM files for the TLS tests."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    subject = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1"))]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_path = tmp_path / f"{name}.crt"
    key_path = tmp_path / f"{name}.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )
    )
    return str(cert_path), str(key_path)


@requires_crypto
def test_ops_tls_serves_https_and_rejects_plain(tmp_path):
    import ssl

    cert, key = _self_signed(tmp_path, "ops")
    system = System(
        Options(listen_address="127.0.0.1:0", tls_cert_file=cert, tls_key_file=key)
    )
    addr = system.start()
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with urllib.request.urlopen(
            f"https://{addr}/version", context=ctx, timeout=5
        ) as resp:
            assert json.loads(resp.read())["Version"]
        # plain HTTP against the TLS socket must fail
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://{addr}/version", timeout=2)
    finally:
        system.stop()


@requires_crypto
def test_ops_tls_client_auth_required(tmp_path):
    import ssl

    cert, key = _self_signed(tmp_path, "ops")
    ca_cert, _ca_key = _self_signed(tmp_path, "clientca")
    system = System(
        Options(
            listen_address="127.0.0.1:0",
            tls_cert_file=cert,
            tls_key_file=key,
            client_ca_file=ca_cert,
        )
    )
    addr = system.start()
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"https://{addr}/version", context=ctx, timeout=5
            )
    finally:
        system.stop()


# ---------------- committer metrics (kvledger/metrics.go) -----------------


def test_committer_metrics_families():
    from fabric_tpu.ledger.ledgermetrics import CommitterMetrics
    from fabric_tpu.validation.txflags import TxValidationCode, ValidationFlags

    provider = PrometheusProvider()
    metrics = CommitterMetrics(provider)
    flags = ValidationFlags(3, TxValidationCode.VALID)
    flags.set_flag(1, TxValidationCode.MVCC_READ_CONFLICT)
    metrics.observe_commit("ch1", flags, 7, 0.010, 0.002, 0.003)
    text = provider.gather()
    assert 'ledger_blockchain_height{channel="ch1"} 7' in text
    assert "ledger_block_processing_time" in text
    assert (
        'ledger_transaction_count{channel="ch1",validation_code="VALID"} 2'
        in text
    )
    assert (
        'ledger_transaction_count{channel="ch1",'
        'validation_code="MVCC_READ_CONFLICT"} 1' in text
    )


def test_ops_pprof_disabled_by_default(ops_system):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(ops_system, "/debug/pprof/goroutine")
    assert exc.value.code == 404


def test_ops_pprof_endpoints(tmp_path):
    """Go-pprof analogs (orderer main.go:458 Profile service): thread
    dump, sampled CPU profile, heap snapshot."""
    system = System(
        Options(listen_address="127.0.0.1:0", profile_enabled=True)
    )
    system.start()
    try:
        with _get(system, "/debug/pprof/") as resp:
            assert b"profile" in resp.read()
        with _get(system, "/debug/pprof/goroutine") as resp:
            body = resp.read().decode()
        assert "thread" in body and "operations" in body
        with _get(system, "/debug/pprof/profile?seconds=0.2") as resp:
            assert b"cpu profile" in resp.read()
        with _get(system, "/debug/pprof/heap") as resp:
            assert resp.status == 200
    finally:
        system.stop()
        flogging.reset()
