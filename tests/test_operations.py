"""Operations plane: metrics SPI, flogging level specs, ops HTTP server
(reference core/operations/system.go, common/flogging, common/metrics)."""

import json
import urllib.request

import pytest

from conftest import requires_crypto

from fabric_tpu.common import flogging
from fabric_tpu.common.metrics import (
    CounterOpts,
    DisabledProvider,
    GaugeOpts,
    HistogramOpts,
    PrometheusProvider,
    StatsdProvider,
)
from fabric_tpu.operations import Options, System


# ---------------- flogging ----------------


def test_flogging_spec_roundtrip():
    flogging.activate_spec("gossip=warn:ledger.state=debug:info")
    assert flogging.spec() == "gossip=warn:ledger.state=debug:info"
    flogging.reset()
    assert flogging.spec() == "info"


def test_flogging_levels_apply_to_subtrees():
    flogging.activate_spec("gossip=error:debug")
    import logging

    assert flogging.must_get_logger("gossip").level == logging.ERROR
    assert flogging.must_get_logger("gossip.state").level == logging.ERROR
    assert flogging.must_get_logger("ledger").level == logging.DEBUG
    flogging.reset()


def test_flogging_invalid_spec_rejected():
    with pytest.raises(flogging.InvalidSpecError):
        flogging.activate_spec("gossip=notalevel")
    with pytest.raises(flogging.InvalidSpecError):
        flogging.activate_spec("=debug")


# ---------------- metrics ----------------


def test_prometheus_counter_and_gauge():
    p = PrometheusProvider()
    c = p.new_counter(
        CounterOpts(
            namespace="ledger",
            name="transaction_count",
            help="tx count",
            label_names=("channel", "validation_code"),
        )
    )
    c.with_labels("channel", "ch1", "validation_code", "VALID").add()
    c.with_labels("channel", "ch1", "validation_code", "VALID").add(2)
    c.with_labels("channel", "ch1", "validation_code", "MVCC_READ_CONFLICT").add()
    g = p.new_gauge(GaugeOpts(namespace="gossip", name="peers_known"))
    g.set(4)
    text = p.gather()
    assert (
        'ledger_transaction_count{channel="ch1",validation_code="VALID"} 3'
        in text
    )
    assert "# TYPE ledger_transaction_count counter" in text
    assert "gossip_peers_known 4" in text


def test_prometheus_histogram_buckets():
    p = PrometheusProvider()
    h = p.new_histogram(
        HistogramOpts(
            namespace="ledger",
            name="block_processing_time",
            buckets=(0.1, 1.0, 10.0),
        )
    )
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    text = p.gather()
    assert 'ledger_block_processing_time_bucket{le="0.1"} 1' in text
    assert 'ledger_block_processing_time_bucket{le="1"} 3' in text
    assert 'ledger_block_processing_time_bucket{le="10"} 4' in text
    assert 'ledger_block_processing_time_bucket{le="+Inf"} 5' in text
    assert "ledger_block_processing_time_count 5" in text


def test_prometheus_rejects_kind_mismatch():
    p = PrometheusProvider()
    p.new_counter(CounterOpts(name="x"))
    with pytest.raises(ValueError):
        p.new_gauge(GaugeOpts(name="x"))


def test_statsd_provider_formats_buckets():
    lines = []
    p = StatsdProvider(lines.append, prefix="peer0")
    c = p.new_counter(
        CounterOpts(
            namespace="ledger",
            name="tx_count",
            label_names=("channel",),
            statsd_format="%{#fqname}.%{channel}",
        )
    )
    c.with_labels("channel", "ch1").add()
    assert lines == ["peer0.ledger.tx.count.ch1:1|c"]


def test_disabled_provider_noops():
    p = DisabledProvider()
    p.new_counter(CounterOpts(name="c")).add()
    p.new_gauge(GaugeOpts(name="g")).set(1)
    p.new_histogram(HistogramOpts(name="h")).observe(1)


# ---------------- operations server ----------------


@pytest.fixture
def ops_system():
    system = System(Options(listen_address="127.0.0.1:0"))
    system.start()
    yield system
    system.stop()
    flogging.reset()


def _get(system, path):
    return urllib.request.urlopen(f"http://{system.addr}{path}")


def test_ops_version_and_metrics(ops_system):
    with _get(ops_system, "/version") as resp:
        assert json.load(resp)["Version"]
    ops_system.provider.new_counter(CounterOpts(name="up")).add()
    with _get(ops_system, "/metrics") as resp:
        assert b"up 1" in resp.read()


def test_ops_healthz(ops_system):
    with _get(ops_system, "/healthz") as resp:
        assert json.load(resp)["status"] == "OK"

    def failing():
        raise RuntimeError("couchdb down")

    ops_system.register_checker("statedb", failing)
    try:
        _get(ops_system, "/healthz")
        assert False, "expected 503"
    except urllib.error.HTTPError as err:
        payload = json.load(err)
        assert payload["failed_checks"] == [
            {"component": "statedb", "reason": "couchdb down"}
        ]


def test_ops_logspec_get_and_put(ops_system):
    req = urllib.request.Request(
        f"http://{ops_system.addr}/logspec",
        data=json.dumps({"spec": "gossip=debug:warn"}).encode(),
        method="PUT",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 204
    with _get(ops_system, "/logspec") as resp:
        assert json.load(resp)["spec"] == "gossip=debug:warn"

    bad = urllib.request.Request(
        f"http://{ops_system.addr}/logspec",
        data=json.dumps({"spec": "nope=nope"}).encode(),
        method="PUT",
    )
    try:
        urllib.request.urlopen(bad)
        assert False, "expected 400"
    except urllib.error.HTTPError as err:
        assert err.code == 400


# ---------------- operations TLS (core/operations/system.go TLS) ----------


def _self_signed(tmp_path, name):
    """Self-signed cert + key PEM files for the TLS tests."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    subject = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1"))]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_path = tmp_path / f"{name}.crt"
    key_path = tmp_path / f"{name}.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )
    )
    return str(cert_path), str(key_path)


@requires_crypto
def test_ops_tls_serves_https_and_rejects_plain(tmp_path):
    import ssl

    cert, key = _self_signed(tmp_path, "ops")
    system = System(
        Options(listen_address="127.0.0.1:0", tls_cert_file=cert, tls_key_file=key)
    )
    addr = system.start()
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with urllib.request.urlopen(
            f"https://{addr}/version", context=ctx, timeout=5
        ) as resp:
            assert json.loads(resp.read())["Version"]
        # plain HTTP against the TLS socket must fail
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://{addr}/version", timeout=2)
    finally:
        system.stop()


@requires_crypto
def test_ops_tls_client_auth_required(tmp_path):
    import ssl

    cert, key = _self_signed(tmp_path, "ops")
    ca_cert, _ca_key = _self_signed(tmp_path, "clientca")
    system = System(
        Options(
            listen_address="127.0.0.1:0",
            tls_cert_file=cert,
            tls_key_file=key,
            client_ca_file=ca_cert,
        )
    )
    addr = system.start()
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"https://{addr}/version", context=ctx, timeout=5
            )
    finally:
        system.stop()


# ---------------- committer metrics (kvledger/metrics.go) -----------------


def test_committer_metrics_families():
    from fabric_tpu.ledger.ledgermetrics import CommitterMetrics
    from fabric_tpu.validation.txflags import TxValidationCode, ValidationFlags

    provider = PrometheusProvider()
    metrics = CommitterMetrics(provider)
    flags = ValidationFlags(3, TxValidationCode.VALID)
    flags.set_flag(1, TxValidationCode.MVCC_READ_CONFLICT)
    metrics.observe_commit("ch1", flags, 7, 0.010, 0.002, 0.003)
    text = provider.gather()
    assert 'ledger_blockchain_height{channel="ch1"} 7' in text
    assert "ledger_block_processing_time" in text
    assert (
        'ledger_transaction_count{channel="ch1",validation_code="VALID"} 2'
        in text
    )
    assert (
        'ledger_transaction_count{channel="ch1",'
        'validation_code="MVCC_READ_CONFLICT"} 1' in text
    )


def test_ops_pprof_disabled_by_default(ops_system):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(ops_system, "/debug/pprof/goroutine")
    assert exc.value.code == 404


def test_ops_pprof_endpoints(tmp_path):
    """Go-pprof analogs (orderer main.go:458 Profile service): thread
    dump, sampled CPU profile, heap snapshot."""
    system = System(
        Options(listen_address="127.0.0.1:0", profile_enabled=True)
    )
    system.start()
    try:
        with _get(system, "/debug/pprof/") as resp:
            assert b"profile" in resp.read()
        with _get(system, "/debug/pprof/goroutine") as resp:
            body = resp.read().decode()
        assert "thread" in body and "operations" in body
        with _get(system, "/debug/pprof/profile?seconds=0.2") as resp:
            assert b"cpu profile" in resp.read()
        with _get(system, "/debug/pprof/heap") as resp:
            assert resp.status == 200
    finally:
        system.stop()
        flogging.reset()
