"""Per-class QoS admission (serve/qos.py + the sidecar's rev-2 path):
ledger quota/borrowing/demand-latch semantics, the channel->class map,
the retry_after_ms fill scaling (previously untested PR 8 behavior),
protocol version negotiation, and drain (rolling restart) mask rules."""

import threading
import time

import pytest

from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.qos import (
    ClassLedger,
    class_for_channel,
    parse_qos_map,
    parse_shares,
)
from fabric_tpu.serve.server import SidecarServer

from tests.test_serve import mixed_lanes


class TestClassLedger:
    def test_quota_split(self):
        led = ClassLedger(100, {"high": 0.5, "normal": 0.3, "bulk": 0.2})
        snap = led.snapshot()
        assert snap["high"]["quota"] == 50
        assert snap["normal"]["quota"] == 30
        assert snap["bulk"]["quota"] == 20

    def test_single_class_uses_full_budget_when_others_idle(self):
        """Work-conserving: an idle class protects nothing — one tenant
        can occupy the whole machine."""
        led = ClassLedger(100)
        assert led.try_acquire(proto.QOS_BULK, 60)
        assert led.try_acquire(proto.QOS_BULK, 40)
        assert not led.try_acquire(proto.QOS_BULK, 1)  # budget truly full

    def test_rejection_latches_reservation(self):
        """After ONE high-priority rejection, bulk can no longer borrow
        the high quota; the high retry admits in full."""
        led = ClassLedger(100, {"high": 0.5, "normal": 0.3, "bulk": 0.2})
        assert led.try_acquire(proto.QOS_BULK, 100)  # idle fleet: all of it
        assert not led.try_acquire(proto.QOS_HIGH, 50)  # sheds, latches
        led.release(proto.QOS_BULK, 100)
        # bulk may refill only what leaves the 50-lane reservation free
        assert led.try_acquire(proto.QOS_BULK, 50)
        assert not led.try_acquire(proto.QOS_BULK, 10)
        assert led.try_acquire(proto.QOS_HIGH, 50)  # reserved lanes held
        snap = led.snapshot()
        assert snap["high"]["waiting"] is False  # cleared by the admission

    def test_guaranteed_share_always_admits(self):
        led = ClassLedger(100, {"high": 0.5, "normal": 0.3, "bulk": 0.2})
        assert led.try_acquire(proto.QOS_BULK, 20)
        assert led.try_acquire(proto.QOS_NORMAL, 30)
        assert led.try_acquire(proto.QOS_HIGH, 50)

    def test_release_clamps_and_unknown_class_maps_to_bulk(self):
        led = ClassLedger(10)
        led.release(proto.QOS_HIGH, 5)  # release without acquire: no-op
        assert led.fill() == 0.0
        assert led.try_acquire(99, 2)  # unknown id -> bulk, never priority
        assert led.snapshot()["bulk"]["used"] == 2

    def test_oversized_request_is_capped_not_impossible(self):
        led = ClassLedger(64)
        assert led.try_acquire(proto.QOS_NORMAL, 10_000)
        led.release(proto.QOS_NORMAL, 10_000)
        assert led.fill() == 0.0

    def test_parse_shares(self):
        assert parse_shares("high=0.6,bulk=0.1") == {
            "high": 0.6, "bulk": 0.1,
        }
        with pytest.raises(ValueError):
            parse_shares("vip=0.5")
        with pytest.raises(ValueError):
            parse_shares("high=0.9,normal=0.9")


class TestQosMap:
    def test_exact_prefix_and_default(self):
        m = parse_qos_map("paychan=high;spam*=bulk;*=normal")
        assert class_for_channel("paychan", m) == proto.QOS_HIGH
        assert class_for_channel("spam42", m) == proto.QOS_BULK
        assert class_for_channel("other", m) == proto.QOS_NORMAL

    def test_longest_prefix_wins_and_fallback(self):
        m = parse_qos_map("spam*=bulk;spamvip*=high")
        assert class_for_channel("spamvip1", m) == proto.QOS_HIGH
        assert class_for_channel("spam1", m) == proto.QOS_BULK
        assert class_for_channel("x", m) == proto.DEFAULT_QOS
        assert class_for_channel(None, {}) == proto.DEFAULT_QOS

    def test_malformed_map_raises(self):
        with pytest.raises(ValueError):
            parse_qos_map("chan=vip")

    def test_env_map_malformed_warns_and_defaults(self, monkeypatch):
        from fabric_tpu.serve.qos import qos_map_from_env

        monkeypatch.setenv("FABRIC_TPU_SERVE_QOS", "chan==nope==")
        with pytest.warns(RuntimeWarning):
            assert qos_map_from_env() == {}


class _FakeBatcher:
    """pending_lanes stub for the fill-scaling unit (the real batcher's
    fill is timing-dependent; the scaling FORMULA is what's pinned)."""

    def __init__(self, pending):
        self.pending_lanes = pending


class TestRetryAfterScaling:
    """serve/server.py retry_after_ms — the fill scaling shipped in
    PR 8 without a test, plus the per-class extension."""

    @pytest.fixture
    def server(self, tmp_path):
        srv = SidecarServer(
            str(tmp_path / "ra.sock"), engine="host", warm_ladder="off",
            max_pending_lanes=100, retry_after_base_ms=25,
        )
        real = srv.batcher
        yield srv
        srv.batcher = real
        srv.stop()

    def test_fill_scales_hint_linearly(self, server):
        server.batcher = _FakeBatcher(0)
        assert server.retry_after_ms() == 25  # base at zero fill
        server.batcher = _FakeBatcher(50)
        assert server.retry_after_ms() == int(25 * (1.0 + 1.5))
        server.batcher = _FakeBatcher(100)
        assert server.retry_after_ms() == 25 * 4  # saturated: 4x base
        # monotone in fill, floored at 5ms
        hints = []
        for pending in (0, 25, 75, 100):
            server.batcher = _FakeBatcher(pending)
            hints.append(server.retry_after_ms())
        assert hints == sorted(hints) and hints[0] >= 5

    def test_class_fill_dominates_global_fill(self, server):
        server.batcher = _FakeBatcher(0)
        # saturate the bulk quota only: bulk's hint inflates, high's
        # stays at base (its own quota is idle)
        bulk_quota = server.qos.snapshot()["bulk"]["quota"]
        assert server.qos.try_acquire(proto.QOS_BULK, bulk_quota)
        try:
            assert server.retry_after_ms(proto.QOS_BULK) == 25 * 4
            assert server.retry_after_ms(proto.QOS_HIGH) == 25
        finally:
            server.qos.release(proto.QOS_BULK, bulk_quota)


class TestServerQosPath:
    """End-to-end rev-2 serving: class accounting, v1 compatibility,
    and drain semantics."""

    @pytest.fixture
    def sidecar(self, tmp_path):
        srv = SidecarServer(
            str(tmp_path / "qos.sock"), engine="host", warm_ladder="off",
            buckets=(64, 256),
        )
        srv.warm()
        srv.start()
        yield srv
        srv.stop()

    def test_classed_requests_land_in_class_stats(self, sidecar):
        from fabric_tpu.serve.client import SidecarProvider

        provider = SidecarProvider(
            address=sidecar.address, qos_class=proto.QOS_HIGH,
            channel="paychan",
        )
        k, s, d, e = mixed_lanes(20)
        assert list(provider.batch_verify(k, s, d)) == e
        summary = sidecar.stats.summary()
        assert summary["per_class"]["high"]["served"] == 1
        assert summary["per_class"]["high"]["lanes"] == 20
        provider.stop()

    def test_v1_client_still_served_as_default_class(self, sidecar):
        """Old-client compatibility: a hand-rolled v1 frame (no QoS
        prefix) verifies fine and accounts as the default class."""
        import socket as _socket

        from fabric_tpu.serve.client import encode_lanes

        family, target = proto.parse_address(sidecar.address)
        sock = _socket.socket(family, _socket.SOCK_STREAM)
        sock.connect(target)
        try:
            k, s, d, e = mixed_lanes(10)
            payload = encode_lanes(k, s, d, qos_class=None)  # v1 body
            proto.send_frame(sock, proto.OP_VERIFY, 7, payload, version=1)
            frame = proto.recv_frame_ex(sock)
            assert frame is not None
            _op, rid, reply, version = frame
            assert rid == 7 and version == 1  # reply echoes v1
            status, _, mask, _ = proto.decode_verify_response(reply)
            assert status == proto.ST_OK and mask == e
        finally:
            sock.close()
        assert sidecar.stats.summary()["per_class"]["normal"]["served"] == 1

    def test_client_negotiates_v2_against_new_server(self, sidecar):
        from fabric_tpu.serve.client import SidecarClient

        client = SidecarClient(sidecar.address)
        assert client.ping()
        assert client.version == proto.PROTOCOL_VERSION
        client.close()

    def test_client_downgrades_to_v1_against_old_server(self, tmp_path):
        """A v1-only server (the PR 8 behavior: unsupported version ->
        one ST_ERROR frame, stream closed) makes the hello latch v1 —
        new clients keep working against old sidecars, minus QoS."""
        import socket as _socket
        import struct

        from fabric_tpu.serve.client import SidecarClient

        addr = str(tmp_path / "old.sock")
        listener = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        listener.bind(addr)
        listener.listen(4)
        stop = threading.Event()

        def old_server():
            while not stop.is_set():
                try:
                    conn, _ = listener.accept()
                except OSError:
                    return
                try:
                    while True:
                        head = conn.recv(proto.HEADER_SIZE)
                        if len(head) < proto.HEADER_SIZE:
                            break
                        magic, ver, op, rid, length = struct.unpack(
                            ">2sBBII", head
                        )
                        if length:
                            conn.recv(length)
                        if ver != 1:
                            # the old server's refusal: one error
                            # frame (v1 header), then close
                            conn.sendall(proto.pack_frame(
                                proto.OP_VERIFY, 0,
                                proto.encode_verify_response(
                                    proto.ST_ERROR,
                                    message="unsupported protocol version",
                                ),
                                version=1,
                            ))
                            break
                        if op == proto.OP_PING:
                            conn.sendall(proto.pack_frame(
                                proto.OP_PING, rid,
                                proto.encode_verify_response(
                                    proto.ST_OK, mask=[]
                                ),
                                version=1,
                            ))
                finally:
                    conn.close()

        server_thread = threading.Thread(target=old_server, daemon=True)
        server_thread.start()
        try:
            client = SidecarClient(addr)
            assert client.ping()
            assert client.version == proto.MIN_PROTOCOL_VERSION
            client.close()
        finally:
            stop.set()
            listener.close()
            server_thread.join(timeout=5.0)

    def test_silent_hello_eof_does_not_downgrade(self, tmp_path):
        """A sidecar restarting under the dial (connect OK, stream
        closed before the hello reply) is a TRANSPORT failure, not a
        version refusal — the client must keep v2, or a transient
        crash window would permanently strip the QoS class."""
        import socket as _socket

        from fabric_tpu.serve.client import SidecarClient, SidecarUnavailable

        addr = str(tmp_path / "flap.sock")
        listener = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        listener.bind(addr)
        listener.listen(1)
        stop = threading.Event()

        def close_on_accept():
            while not stop.is_set():
                try:
                    conn, _ = listener.accept()
                except OSError:
                    return
                conn.close()  # the crash window: no frame, just EOF

        t = threading.Thread(target=close_on_accept, daemon=True)
        t.start()
        try:
            client = SidecarClient(addr)
            with pytest.raises(SidecarUnavailable):
                client.ensure_connected()
            assert client.version == proto.PROTOCOL_VERSION  # NOT latched
            client.close()
        finally:
            stop.set()
            listener.close()
            t.join(timeout=5.0)

    def test_drain_refuses_new_work_and_settles_in_flight(self, tmp_path):
        """The rolling-restart contract: after drain() starts, NEW
        verify work answers ST_STOPPING while an in-flight request
        settles with its REAL verdicts (never fail-closed)."""
        from fabric_tpu.crypto.bccsp import SoftwareProvider
        from fabric_tpu.serve.client import SidecarClient, encode_lanes

        gate = threading.Event()
        entered = threading.Event()

        class Gated(SoftwareProvider):
            def batch_verify_async(self, keys, sigs, digests):
                out = SoftwareProvider.batch_verify(self, keys, sigs, digests)
                entered.set()
                gate.wait(10.0)
                return lambda: out

        server = SidecarServer(
            str(tmp_path / "drain.sock"), engine="host", provider=Gated(),
            warm_ladder="off", buckets=(64,), linger_s=0.0,
        )
        server.start()
        client = SidecarClient(server.address)
        try:
            k, s, d, e = mixed_lanes(30)
            token = client.submit(proto.OP_VERIFY, encode_lanes(k, s, d))
            assert entered.wait(5.0)
            drainer = threading.Thread(
                target=server.drain, kwargs={"timeout_s": 10.0}, daemon=True
            )
            drainer.start()
            deadline = time.monotonic() + 5.0
            while not server._draining and time.monotonic() < deadline:
                time.sleep(0.01)
            # new work while draining: explicit ST_STOPPING
            k2, s2, d2, _e2 = mixed_lanes(10, seed=2)
            tok2 = client.submit(proto.OP_VERIFY, encode_lanes(k2, s2, d2))
            status2, _, _, _ = proto.decode_verify_response(
                client.await_reply(tok2)
            )
            assert status2 == proto.ST_STOPPING
            # the in-flight request settles with its real mask
            gate.set()
            status1, _, mask1, _ = proto.decode_verify_response(
                client.await_reply(token)
            )
            assert status1 == proto.ST_OK and mask1 == e
            drainer.join(timeout=5.0)
            assert not drainer.is_alive()
        finally:
            gate.set()
            client.close()
            server.stop()

    def test_op_drain_acks_then_stops(self, sidecar):
        from fabric_tpu.serve.client import SidecarClient

        client = SidecarClient(sidecar.address)
        status, _, _, _ = proto.decode_verify_response(
            client.request(proto.OP_DRAIN)
        )
        assert status == proto.ST_OK
        client.close()
        deadline = time.monotonic() + 5.0
        while not sidecar._stopping and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sidecar._stopping
