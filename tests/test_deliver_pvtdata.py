"""DeliverWithPrivateData (reference core/peer/deliverevents.go:270):
block responses carry the peer's stored cleartext private rwsets keyed by
tx index; blocks without stored private data have empty maps."""

from fabric_tpu.deliver.server import (
    BlockSource,
    DeliverHandler,
    deliver_with_pvtdata,
    pvt_data_map,
)
from fabric_tpu.ledger.pvtdatastore import PvtEntry
from fabric_tpu.protos import ab_pb2, common_pb2, protoutil


def _seek_env(channel: str, start: int, stop: int) -> common_pb2.Envelope:
    seek = ab_pb2.SeekInfo()
    seek.start.specified.number = start
    seek.stop.specified.number = stop
    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(common_pb2.DELIVER_SEEK_INFO, channel)
    payload.header.channel_header = chdr.SerializeToString()
    payload.data = seek.SerializeToString()
    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()
    return env


def _blocks(n):
    out = []
    prev = b""
    for i in range(n):
        b = protoutil.new_block(i, prev)
        b.data.data.append(b"tx-bytes-%d" % i)
        protoutil.seal_block(b)
        prev = protoutil.block_header_hash(b.header)
        out.append(b)
    return out


def test_pvt_data_map_groups_by_tx_and_namespace():
    entries = [
        PvtEntry(0, "cc", "collB", b"rw-b"),
        PvtEntry(0, "cc", "collA", b"rw-a"),
        PvtEntry(2, "other", "c", b"rw-c"),
    ]
    m = pvt_data_map(entries)
    assert set(m) == {0, 2}
    tx0 = m[0]
    assert len(tx0.ns_pvt_rwset) == 1
    assert tx0.ns_pvt_rwset[0].namespace == "cc"
    colls = [c.collection_name for c in tx0.ns_pvt_rwset[0].collection_pvt_rwset]
    assert colls == ["collA", "collB"]  # deterministic order
    assert m[2].ns_pvt_rwset[0].namespace == "other"


def test_deliver_with_pvtdata_attaches_maps():
    blocks = _blocks(3)
    handler = DeliverHandler(
        lambda cid: BlockSource(
            lambda n: blocks[n] if n < len(blocks) else None,
            lambda: len(blocks),
        )
        if cid == "ch"
        else None
    )
    stored = {
        1: [PvtEntry(0, "cc", "secret", b"pvt-rwset-bytes")],
    }

    def pvt_entries(channel_id, block_num):
        assert channel_id == "ch"
        return stored.get(block_num, [])

    resps = list(
        deliver_with_pvtdata(handler, _seek_env("ch", 0, 2), pvt_entries)
    )
    # 3 blocks + SUCCESS status
    assert len(resps) == 4
    assert resps[3].status == common_pb2.SUCCESS
    kinds = [r.WhichOneof("Type") for r in resps[:3]]
    assert kinds == ["block_and_private_data"] * 3
    b1 = resps[1].block_and_private_data
    assert b1.block.header.number == 1
    assert list(b1.private_data_map) == [0]
    coll = b1.private_data_map[0].ns_pvt_rwset[0].collection_pvt_rwset[0]
    assert coll.collection_name == "secret"
    assert coll.rwset == b"pvt-rwset-bytes"
    # blocks without stored pvtdata: empty map, like the reference
    assert not resps[0].block_and_private_data.private_data_map
    assert not resps[2].block_and_private_data.private_data_map


def test_policy_checker_gates_the_stream():
    """With a policy checker configured, unsigned requests and rejected
    identities get FORBIDDEN and zero blocks (the stream exposes private
    cleartext, unlike plain Deliver)."""
    blocks = _blocks(1)
    handler = DeliverHandler(
        lambda cid: BlockSource(lambda n: blocks[n], lambda: 1)
    )

    def deny(channel_id, sd):
        raise PermissionError("not a reader")

    resps = list(
        deliver_with_pvtdata(handler, _seek_env("ch", 0, 0), lambda c, n: [], deny)
    )
    assert [r.WhichOneof("Type") for r in resps] == ["status"]
    assert resps[0].status == common_pb2.FORBIDDEN
