"""Device Ate2 pairing (ops/pairing_kernel.py) vs the host oracle
(crypto/fp256bn.py): tower ops bit-exact, Miller values bit-exact,
unity verdicts identical on valid/corrupt inputs, and the idemix batch
path equal with device_pairing on and off."""

import os
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fabric_tpu.crypto import fp256bn as host
from fabric_tpu.ops import bignum as bn
from fabric_tpu.ops import fp12 as f12

RNG = random.Random(20260731)

# The pairing program is compiled ONCE for all issuer keys (line
# schedules are runtime inputs), and the persistent compile cache serves
# every later run — but a WARM run of the end-to-end differentials still
# costs minutes of pure XLA:CPU *execution* on the 2-vCPU gate box
# (measured with FABRIC_TPU_CACHE_DEBUG=1: test_ate2_unity hits the
# cache and still takes ~277s; test_ate2_sharded ~508s — see
# NOTES_BUILD).  That is execution cost no cache can amortize, so the
# heavy differentials carry @pytest.mark.slow and tier-1 (-m 'not
# slow') keeps the cheap fp12 tower rung only; full runs (no -m
# filter, CI-external soaks) still execute them.  FABRIC_TPU_PAIRING_TESTS=0
# opts out of the kernel tests entirely; the two deep-debug
# differentials (per-step Miller values, the idemix batch e2e) stay
# behind FABRIC_TPU_PAIRING_TESTS=1.
_mode = os.environ.get("FABRIC_TPU_PAIRING_TESTS", "")
full_kernel = pytest.mark.skipif(
    _mode == "0",
    reason="pairing kernel tests disabled (FABRIC_TPU_PAIRING_TESTS=0)",
)
deep_kernel = pytest.mark.skipif(
    _mode != "1",
    reason="deep pairing differentials are slow; "
    "set FABRIC_TPU_PAIRING_TESTS=1",
)


def rand_fp12():
    return tuple(
        (RNG.randrange(host.P), RNG.randrange(host.P)) for _ in range(6)
    )


def like2():
    return jnp.zeros((2,), dtype=jnp.uint32)


def test_tower_ops_bit_exact():
    x, y = rand_fp12(), rand_fp12()
    with bn.force_looped_cios():
        lk = like2()

        @jax.jit
        def fn(x_st, y_st):
            xx = f12.unpack(x_st)
            yy = f12.unpack(y_st)
            return (
                f12.pack(f12.fp12_mul(xx, yy)),
                f12.pack(f12.fp12_sqr(xx)),
                f12.pack(f12.fp12_frobenius(xx, 1)),
                f12.pack(f12.fp12_frobenius(xx, 2)),
                f12.pack(f12.fp12_conj(xx)),
            )

        outs = fn(
            f12.pack(f12.fp12_from_host(x, lk)),
            f12.pack(f12.fp12_from_host(y, lk)),
        )
        got = [f12.fp12_to_host(f12.unpack(np.asarray(o))) for o in outs]
    assert got[0] == host.fp12_mul(x, y)
    assert got[1] == host.fp12_sqr(x)
    assert got[2] == host.fp12_frobenius(x, 1)
    assert got[3] == host.fp12_frobenius(x, 2)
    assert got[4] == host.fp12_conj(x)


@pytest.mark.slow
def test_inv_and_pow_bit_exact():
    x = rand_fp12()
    e = 0xDEADBEEF12345
    with bn.force_looped_cios():
        lk = like2()

        @jax.jit
        def fn(x_st):
            xx = f12.unpack(x_st)
            return (
                f12.pack(f12.fp12_inv(xx)),
                f12.pack(f12.fp12_pow_const(xx, e)),
            )

        outs = fn(f12.pack(f12.fp12_from_host(x, lk)))
        got = [f12.fp12_to_host(f12.unpack(np.asarray(o))) for o in outs]
    assert got[0] == host.fp12_inv(x)
    assert got[1] == host.fp12_pow(x, e)


def _rand_g1():
    return host.g1_mul(host.G1_GEN, RNG.randrange(1, host.R))


def _rand_g2():
    return host.g2_mul(host.G2_GEN, RNG.randrange(1, host.R))


@deep_kernel
def test_miller_values_bit_exact():
    from fabric_tpu.ops.pairing_kernel import miller2_host_values

    w = _rand_g2()
    p1, p2 = _rand_g1(), _rand_g1()
    got1, got2 = miller2_host_values(w, p1, p2)
    assert got1 == host.miller_loop(w, p1)
    assert got2 == host.miller_loop(host.G2_GEN, p2)


@full_kernel
@pytest.mark.slow
def test_ate2_unity_matches_oracle():
    """e(W, A')·e(g2, ABar)^-1 == 1 holds iff ABar = A'^w-exponent
    structure matches; build a true pair from the BBS+ relation
    ABar = A'·sk-free scaling: use W = g2^gamma, A' random,
    ABar = A'^gamma — then e(W,A') == e(g2, ABar)."""
    from fabric_tpu.ops.pairing_kernel import Ate2Kernel

    gamma = RNG.randrange(1, host.R)
    w = host.g2_mul(host.G2_GEN, gamma)
    kernel = Ate2Kernel(w)

    a1 = _rand_g1()
    good = (a1, host.g1_mul(a1, gamma))
    a2 = _rand_g1()
    bad = (a2, host.g1_mul(a2, (gamma + 1) % host.R))

    def oracle(pair):
        t = host.fp12_mul(
            host.ate(w, pair[0]),
            host.fp12_inv(host.ate(host.G2_GEN, pair[1])),
        )
        return host.gt_is_unity(host.fexp(t))

    assert oracle(good) and not oracle(bad)
    got = kernel.check([good, bad, None])
    assert got == [True, False, False]


@deep_kernel
def test_idemix_batch_device_pairing_matches_host():
    from fabric_tpu import idemix
    from fabric_tpu.crypto import fp256bn as bncurve
    from fabric_tpu.idemix.batch import verify_signatures_batch

    rng = random.Random(1234)
    attrs = ["OU", "Role", "EnrollmentID", "RevocationHandle"]
    rh_index = 3
    ik = idemix.new_issuer_key(attrs, rng)
    sk = bncurve.rand_mod_order(rng)
    nonce = bncurve.big_to_bytes(bncurve.rand_mod_order(rng))
    req = idemix.new_cred_request(sk, nonce, ik.ipk, rng)
    cred = idemix.new_credential(ik, req, [11, 22, 33, 44], rng)
    rev_key = idemix.generate_long_term_revocation_key()
    cri = idemix.create_cri(rev_key, [], 0, idemix.ALG_NO_REVOCATION, rng)
    disclosure = [0, 0, 0, 0]
    msg = b"device pairing test"
    sigs = []
    for _ in range(3):
        nym, r_nym = idemix.make_nym(sk, ik.ipk, rng)
        sigs.append(
            idemix.new_signature(
                cred, sk, nym, r_nym, ik.ipk, disclosure, msg,
                rh_index, cri, rng,
            )
        )
    from fabric_tpu.protos import idemix_pb2

    # corrupt one signature's ABar so the pairing check fails that lane
    from fabric_tpu.idemix.scheme import ecp_from_proto, ecp_to_proto

    bad = idemix_pb2.Signature()
    bad.CopyFrom(sigs[1])
    a_bar = ecp_from_proto(bad.a_bar)
    bad.a_bar.CopyFrom(ecp_to_proto(bncurve.g1_mul(a_bar, 2)))
    sigs[1] = bad

    values = [[None] * 4] * 3
    host_out = verify_signatures_batch(
        sigs, [disclosure] * 3, ik.ipk, [msg] * 3, values, rh_index,
        device_pairing=False,
    )
    dev_out = verify_signatures_batch(
        sigs, [disclosure] * 3, ik.ipk, [msg] * 3, values, rh_index,
        device_pairing=True,
    )
    assert host_out == dev_out
    assert dev_out[0] is True or dev_out[0] == True  # noqa: E712
    assert not dev_out[1]


@full_kernel
@pytest.mark.slow
def test_ate2_sharded_matches_single_device():
    """Lane-sharded pairing over an 8-device mesh (SURVEY P6: the
    multi-chip scale-out of the idemix verify column) agrees lane-exact
    with the single-device program."""
    import jax

    from fabric_tpu.ops.pairing_kernel import Ate2Kernel
    from fabric_tpu.parallel.mesh import flat_mesh

    gamma = RNG.randrange(1, host.R)
    w = host.g2_mul(host.G2_GEN, gamma)
    kernel = Ate2Kernel(w)

    pairs = []
    for i in range(11):  # odd count: exercises padding to 16 lanes
        a = _rand_g1()
        if i % 3 == 2:
            pairs.append((a, host.g1_mul(a, (gamma + 1) % host.R)))
        elif i % 5 == 4:
            pairs.append(None)
        else:
            pairs.append((a, host.g1_mul(a, gamma)))

    single = kernel.check(list(pairs))
    mesh = flat_mesh(jax.devices("cpu")[:8])
    sharded = kernel.check_sharded(list(pairs), mesh)
    assert sharded == single
    assert True in single and False in single  # mixed verdicts
