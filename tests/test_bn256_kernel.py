"""Differential tests: batched FP256BN G1 kernel vs the host oracle
(fabric_tpu.crypto.fp256bn)."""

import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from fabric_tpu.crypto import fp256bn as host
from fabric_tpu.ops import bignum as bn
from fabric_tpu.ops import bn256_kernel as bk


def rand_scalar():
    return secrets.randbelow(host.R - 1) + 1


def rand_point():
    return host.g1_mul(host.G1_GEN, rand_scalar())


class TestPointOps:
    def test_add_matches_host(self):
        ps = [rand_point() for _ in range(4)] + [None, host.G1_GEN]
        qs = [rand_point() for _ in range(4)] + [host.G1_GEN, host.G1_GEN]
        a = bk.pack_points(ps)
        b = bk.pack_points(qs)

        import jax

        @jax.jit
        def add(a, b):
            p = bk.Point(bk.fe(bn.split(a[0])), bk.fe(bn.split(a[1])), bk.fe(bn.split(a[2])))
            q = bk.Point(bk.fe(bn.split(b[0])), bk.fe(bn.split(b[1])), bk.fe(bn.split(b[2])))
            r = bk.point_add(p, q)
            return jnp.stack([bn.restack(r.x.limbs), bn.restack(bk.fe_norm(r.y).limbs), bn.restack(bk.fe_norm(r.z).limbs)])

        got = bk.unpack_points(add(jnp.asarray(a), jnp.asarray(b)))
        for p, q, g in zip(ps, qs, got):
            want = host.g1_add(p, q)
            assert g == want, (p, q)

    def test_double_matches_host_incl_identity(self):
        ps = [rand_point(), host.G1_GEN, None]
        a = bk.pack_points(ps)

        import jax

        @jax.jit
        def dbl(a):
            p = bk.Point(bk.fe(bn.split(a[0])), bk.fe(bn.split(a[1])), bk.fe(bn.split(a[2])))
            r = bk.point_double(p)
            return jnp.stack([bn.restack(bk.fe_norm(r.x).limbs), bn.restack(bk.fe_norm(r.y).limbs), bn.restack(bk.fe_norm(r.z).limbs)])

        got = bk.unpack_points(dbl(jnp.asarray(a)))
        for p, g in zip(ps, got):
            assert g == host.g1_add(p, p), p


class TestMSM:
    """All cases share ONE (K=4, B=4) shape — every distinct shape is a
    multi-minute XLA compile; identity bases with zero scalars pad the
    smaller cases."""

    K, B = 4, 4

    def _run(self, cases):
        """cases: list of (bases, scalars) with len <= K; padded to (K,B)."""
        while len(cases) < self.B:
            cases.append(([], []))
        bases, scalars = [], []
        for bs, es in cases:
            bs = list(bs) + [None] * (self.K - len(bs))
            es = list(es) + [0] * (self.K - len(es))
            bases.append(bs)
            scalars.append(es)
        got = bk.msm_host_batch(bases, scalars)
        want = []
        for bs, es in zip(bases, scalars):
            acc = None
            for b, e in zip(bs, es):
                acc = host.g1_add(acc, host.g1_mul(b, e % host.R))
            want.append(acc)
        assert got == want

    def test_single_base_matches_scalar_mul(self):
        self._run([([rand_point()], [rand_scalar()]) for _ in range(self.B)])

    def test_multi_base_matches_host_sum(self):
        self._run(
            [
                (
                    [rand_point() for _ in range(self.K)],
                    [rand_scalar() for _ in range(self.K)],
                )
                for _ in range(self.B)
            ]
        )

    def test_edge_scalars_and_identity_base(self):
        self._run(
            [
                ([host.G1_GEN, None], [0, 5]),
                ([host.G1_GEN, host.G1_GEN], [1, host.R - 1]),  # R·G = O
                ([None, None], [3, 7]),
                ([rand_point(), host.G1_GEN], [host.R - 1, 2]),
            ]
        )
