"""fabtail wire deadlines (serve protocol rev 3): v3 framing, the
v1/v2/v3 negotiation downgrade matrix (old server x new client, new
server x old client — deadline/hedge fields dropped cleanly, masks
identical), the server's provably-unfinishable ST_BUSY shed, the
client's budget-derived waits (BUSY retry capped by the remaining
deadline — the PR 14 satellite regression), and the batcher's
deadline-capped linger."""

import struct
import threading
import time

import pytest

from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.client import (
    SidecarClient,
    SidecarProvider,
    deadline_ms_from_env,
    encode_lanes,
)
from fabric_tpu.serve.server import SidecarServer

from tests.test_serve import mixed_lanes


@pytest.fixture
def sidecar(tmp_path):
    addr = str(tmp_path / "dl.sock")
    server = SidecarServer(addr, engine="host", warm_ladder="off",
                           buckets=(64, 256))
    server.warm()
    server.start()
    yield server
    server.stop()


# ---------------------------------------------------------------------------
# protocol rev 3 framing
# ---------------------------------------------------------------------------


class TestProtocolV3:
    TABLE = [b"\x04" + b"\x01" * 64]
    LANES = [(0, b"sig", b"d" * 32), (proto.NO_KEY, b"", b"e" * 32)]

    def test_deadline_roundtrip(self):
        payload = proto.encode_verify_request(
            self.TABLE, self.LANES, qos_class=proto.QOS_HIGH,
            channel="paychan", deadline_ms=1234,
        )
        keys, lanes, qos, chan, dl = proto.decode_verify_request(
            payload, version=3
        )
        assert (keys, lanes) == (self.TABLE, self.LANES)
        assert (qos, chan, dl) == (proto.QOS_HIGH, "paychan", 1234)

    def test_zero_deadline_means_none(self):
        payload = proto.encode_verify_request(
            self.TABLE, self.LANES, qos_class=proto.QOS_NORMAL,
            deadline_ms=0,
        )
        *_rest, dl = proto.decode_verify_request(payload, version=3)
        assert dl == 0

    def test_pre_v3_bodies_carry_no_deadline_bytes(self):
        """The v1/v2 layouts are byte-identical to their PR 12 shapes:
        the deadline field exists only on v3 bodies."""
        v2 = proto.encode_verify_request(
            self.TABLE, self.LANES, qos_class=proto.QOS_BULK
        )
        v3 = proto.encode_verify_request(
            self.TABLE, self.LANES, qos_class=proto.QOS_BULK, deadline_ms=7
        )
        assert len(v3) == len(v2) + 4
        *_r2, dl2 = proto.decode_verify_request(v2, version=2)
        assert dl2 == 0  # old body: no budget, never an error
        v1 = proto.encode_verify_request(self.TABLE, self.LANES)
        *_r1, dl1 = proto.decode_verify_request(v1, version=1)
        assert dl1 == 0

    def test_deadline_requires_qos_prefix(self):
        with pytest.raises(proto.ProtocolError, match="QoS prefix"):
            proto.encode_verify_request(
                self.TABLE, self.LANES, qos_class=None, deadline_ms=5
            )

    def test_encode_lanes_version_picks_body_layout(self):
        k, s, d, _e = mixed_lanes(4)
        for version in (1, 2, 3):
            payload = encode_lanes(k, s, d, version=version)
            out = proto.decode_verify_request(payload, version=version)
            assert len(out[1]) == 4
        # the v1 and v2 bodies must be what an old decoder expects
        assert encode_lanes(k, s, d, version=1) == encode_lanes(
            k, s, d, qos_class=None
        )

    def test_cancel_opcode_value_is_v3(self):
        assert proto.OP_CANCEL == 6
        assert proto.PROTOCOL_VERSION == 3


# ---------------------------------------------------------------------------
# negotiation downgrade matrix
# ---------------------------------------------------------------------------


def _old_server(addr, max_version):
    """A protocol-vN-capped sidecar fake: refuses frames above
    ``max_version`` with one v1 ST_ERROR frame then closes (the PR 8
    behavior a real old binary exhibits), answers PING, and serves
    VERIFY through the real decode + SoftwareProvider so masks are
    comparable bit-exactly against a current server."""
    import socket as _socket

    listener = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    listener.bind(addr)
    listener.listen(8)
    stop = threading.Event()
    sw = SoftwareProvider()

    def serve_conn(conn):
        try:
            while not stop.is_set():
                head = b""
                while len(head) < proto.HEADER_SIZE:
                    chunk = conn.recv(proto.HEADER_SIZE - len(head))
                    if not chunk:
                        return
                    head += chunk
                _magic, ver, op, rid, length = struct.unpack(
                    ">2sBBII", head
                )
                payload = b""
                while len(payload) < length:
                    chunk = conn.recv(length - len(payload))
                    if not chunk:
                        return
                    payload += chunk
                if ver > max_version:
                    conn.sendall(proto.pack_frame(
                        proto.OP_VERIFY, 0,
                        proto.encode_verify_response(
                            proto.ST_ERROR,
                            message="unsupported protocol version",
                        ),
                        version=1,
                    ))
                    return
                if op == proto.OP_PING:
                    conn.sendall(proto.pack_frame(
                        proto.OP_PING, rid,
                        proto.encode_verify_response(proto.ST_OK, mask=[]),
                        version=ver,
                    ))
                elif op == proto.OP_VERIFY:
                    from fabric_tpu.common import p256 as _p256
                    from fabric_tpu.crypto.bccsp import ECDSAPublicKey

                    key_bytes, lanes, _q, _c, dl = (
                        proto.decode_verify_request(payload, ver)
                    )
                    assert dl == 0, "an old server must never see a deadline"
                    keys = []
                    for raw in key_bytes:
                        try:
                            keys.append(
                                ECDSAPublicKey(*_p256.pubkey_from_bytes(raw))
                            )
                        except Exception:  # noqa: BLE001 - dead lane
                            keys.append(None)
                    ks = [
                        keys[i] if i != proto.NO_KEY else None
                        for i, _, _ in lanes
                    ]
                    mask = sw.batch_verify(
                        ks, [s for _, s, _ in lanes], [d for _, _, d in lanes]
                    )
                    conn.sendall(proto.pack_frame(
                        proto.OP_VERIFY, rid,
                        proto.encode_verify_response(proto.ST_OK, mask=mask),
                        version=ver,
                    ))
        finally:
            conn.close()

    def accept_loop():
        while not stop.is_set():
            try:
                conn, _ = listener.accept()
            except OSError:
                return
            threading.Thread(
                target=serve_conn, args=(conn,), daemon=True
            ).start()

    t = threading.Thread(target=accept_loop, daemon=True)
    t.start()

    def teardown():
        stop.set()
        listener.close()
        t.join(timeout=5.0)

    return teardown


class TestNegotiationMatrix:
    @pytest.mark.parametrize("max_version", [1, 2])
    def test_new_client_steps_down_to_old_server(self, tmp_path, max_version):
        """v3 client x vN-only server: the hello steps down ONE
        revision per refusal, the deadline (and QoS, at v1) fields are
        dropped cleanly, and masks are identical to the in-process
        ground truth."""
        addr = str(tmp_path / f"old{max_version}.sock")
        teardown = _old_server(addr, max_version)
        try:
            provider = SidecarProvider(address=addr, deadline_ms=5000)
            k, s, d, e = mixed_lanes(20)
            mask = provider.batch_verify(k, s, d)
            assert list(mask) == e
            assert provider.client.version == max_version
            assert not provider.degraded
            provider.stop()
        finally:
            teardown()

    @pytest.mark.parametrize("version", [1, 2])
    def test_old_client_against_new_server(self, sidecar, version):
        """vN client x v3 server: the raw old-style frame (no deadline,
        no QoS at v1) is served with a mask identical to what a current
        client gets — downgrade-safe both ways."""
        k, s, d, e = mixed_lanes(20, seed=3)
        client = SidecarClient(sidecar.address)
        client.ensure_connected()
        # force the old vintage AFTER the hello (the fake old binary)
        client.version = version
        payload = encode_lanes(k, s, d, version=version)
        status, _, mask, _ = proto.decode_verify_response(
            client.request(proto.OP_VERIFY, payload)
        )
        assert status == proto.ST_OK and list(mask) == e
        client.close()
        # matrix cross-check: the new-protocol mask is identical
        new = SidecarProvider(address=sidecar.address)
        assert list(new.batch_verify(k, s, d)) == e
        new.stop()

    def test_new_pair_negotiates_v3(self, sidecar):
        client = SidecarClient(sidecar.address)
        assert client.ping()
        assert client.version == 3
        client.close()


# ---------------------------------------------------------------------------
# server-side deadline shed
# ---------------------------------------------------------------------------


class TestServerShed:
    def test_no_evidence_no_shed(self, sidecar):
        """A fresh sidecar has no service-time floor for the bucket:
        even a 1ms budget is SERVED (shed only on evidence — a verdict
        computed late beats one refused on a guess)."""
        k, s, d, e = mixed_lanes(16)
        client = SidecarClient(sidecar.address)
        status, _, mask, _ = proto.decode_verify_response(
            client.request(
                proto.OP_VERIFY, encode_lanes(k, s, d, deadline_ms=1)
            )
        )
        assert status == proto.ST_OK and list(mask) == e
        client.close()

    def test_provably_unfinishable_budget_sheds_busy(self, sidecar):
        """Once the bucket's best-ever service time exists, a budget
        below it is shed as an explicit ST_BUSY + retry hint — never a
        silent drop, never a fabricated verdict — and counted apart
        from admission rejects (the qos ledger cross-check)."""
        k, s, d, e = mixed_lanes(64, seed=1)
        client = SidecarClient(sidecar.address)
        status, _, mask, _ = proto.decode_verify_response(
            client.request(proto.OP_VERIFY, encode_lanes(k, s, d))
        )
        assert status == proto.ST_OK and list(mask) == e  # floor learned
        status2, retry_ms, mask2, _ = proto.decode_verify_response(
            client.request(
                proto.OP_VERIFY, encode_lanes(k, s, d, deadline_ms=1)
            )
        )
        assert status2 == proto.ST_BUSY and mask2 is None
        assert retry_ms >= 5
        assert sidecar.stats.deadline_shed == 1
        assert sidecar.stats.rejects == 0  # not an admission reject
        assert sidecar.qos.balance()["leaked"] == 0
        client.close()


# ---------------------------------------------------------------------------
# client budget-derived waits
# ---------------------------------------------------------------------------


def _busy_server(addr):
    """A sidecar fake that answers the hello then replies ST_BUSY with
    an absurd retry_after hint to every VERIFY — the admission-storm
    worst case for a budgeted client."""
    import socket as _socket

    listener = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    listener.bind(addr)
    listener.listen(4)
    stop = threading.Event()

    def serve():
        while not stop.is_set():
            try:
                conn, _ = listener.accept()
            except OSError:
                return
            try:
                while True:
                    frame = proto.recv_frame_ex(conn)
                    if frame is None:
                        break
                    op, rid, _payload, ver = frame
                    if op == proto.OP_PING:
                        body = proto.encode_verify_response(
                            proto.ST_OK, mask=[]
                        )
                    else:
                        body = proto.encode_verify_response(
                            proto.ST_BUSY, retry_after_ms=60_000
                        )
                    conn.sendall(proto.pack_frame(op, rid, body, version=ver))
            except (OSError, proto.ProtocolError):
                pass
            finally:
                conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    def teardown():
        stop.set()
        listener.close()
        t.join(timeout=5.0)

    return teardown


class TestClientBudget:
    def test_busy_retry_capped_by_remaining_deadline(self, tmp_path):
        """The PR 14 satellite regression: the ST_BUSY retry policy is
        a fixed global 10s budget — with a wire deadline it must be
        capped by the REMAINING budget, so a tight-deadline batch fails
        over to the in-process ladder instead of sleeping past it (and
        the server's 60s retry hint must not buy a sleep either)."""
        addr = str(tmp_path / "busy.sock")
        teardown = _busy_server(addr)
        slept = []

        def sleeper(s):
            slept.append(s)
            time.sleep(s)

        try:
            provider = SidecarProvider(
                address=addr, deadline_ms=80, sleeper=sleeper
            )
            k, s, d, e = mixed_lanes(12)
            t0 = time.monotonic()
            mask = provider.batch_verify(k, s, d)
            wall = time.monotonic() - t0
            assert list(mask) == e  # in-process ladder, bit-exact
            assert provider.degraded
            assert provider.deadline_expired == 1
            # every individual pace was bounded by the budget remaining
            # at its moment, and the whole loop gave up around the 80ms
            # budget — nowhere near the 10s global policy (or the 60s
            # server hint)
            assert all(x <= 0.08 + 1e-9 for x in slept)
            assert wall < 5.0
            provider.stop()
        finally:
            teardown()

    def test_no_deadline_keeps_legacy_policy(self, tmp_path):
        """Without a budget the BUSY loop still runs the global policy
        (bounded by max_attempts) — the deadline knob is additive."""
        addr = str(tmp_path / "busy2.sock")
        teardown = _busy_server(addr)
        slept = []
        try:
            provider = SidecarProvider(address=addr, sleeper=slept.append)
            k, s, d, e = mixed_lanes(8)
            assert list(provider.batch_verify(k, s, d)) == e
            assert provider.degraded
            assert provider.deadline_expired == 0
            assert len(slept) > 3  # the policy's retries actually paced
            provider.stop()
        finally:
            teardown()

    def test_expired_budget_hands_back_in_process(self, sidecar):
        """A sidecar that answers but too slowly: the budget-derived
        reply wait walks away and the in-process ladder serves the
        batch bit-exact (degrade, never a guessed verdict)."""
        gate = threading.Event()
        real = sidecar.provider

        class _Slow:
            def batch_verify(self, keys, sigs, digests):
                gate.wait(5.0)
                return real.batch_verify(keys, sigs, digests)

        sidecar.batcher.provider = _Slow()
        try:
            provider = SidecarProvider(address=sidecar.address,
                                       deadline_ms=60)
            k, s, d, e = mixed_lanes(16, seed=2)
            t0 = time.monotonic()
            mask = provider.batch_verify(k, s, d)
            assert list(mask) == e
            assert provider.deadline_expired == 1
            assert time.monotonic() - t0 < 3.0
            provider.stop()
        finally:
            gate.set()
            sidecar.batcher.provider = real

    def test_deadline_env_knob(self, monkeypatch):
        monkeypatch.setenv("FABRIC_TPU_SERVE_DEADLINE_MS", "250")
        assert deadline_ms_from_env() == 250
        monkeypatch.setenv("FABRIC_TPU_SERVE_DEADLINE_MS", "nope")
        assert deadline_ms_from_env() == 0  # malformed: knob disabled
        monkeypatch.delenv("FABRIC_TPU_SERVE_DEADLINE_MS")
        assert deadline_ms_from_env() == 0


# ---------------------------------------------------------------------------
# batcher linger respects the tightest deadline
# ---------------------------------------------------------------------------


class TestBatcherDeadlineLinger:
    def test_tight_deadline_caps_linger(self):
        """A budgeted request must dispatch when its deadline nears,
        not wait out a long linger window hoping for company."""
        from fabric_tpu.parallel.batcher import VerifyBatcher

        b = VerifyBatcher(SoftwareProvider(), linger_s=1.0)
        try:
            k, s, d, e = mixed_lanes(8)
            t0 = time.monotonic()
            resolver = b.try_submit(
                k, s, d, deadline_s=time.monotonic() + 0.05
            )
            assert resolver is not None
            assert list(resolver()) == e
            assert time.monotonic() - t0 < 0.8  # not the 1s linger
        finally:
            b.stop()

    def test_unbudgeted_requests_keep_the_linger(self):
        """No deadline = the PR 8 coalescing behavior, unchanged."""
        from fabric_tpu.parallel.batcher import VerifyBatcher

        b = VerifyBatcher(SoftwareProvider(), linger_s=0.15)
        try:
            k, s, d, e = mixed_lanes(8)
            t0 = time.monotonic()
            assert list(b.verify_batch(k, s, d)) == e
            # the linger window was actually honored (>= one window,
            # generous upper bound for a loaded box)
            assert 0.1 <= time.monotonic() - t0 < 5.0
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# byte-stability drift guard: the full negotiation matrix, field-exact
# ---------------------------------------------------------------------------


class TestCodecDriftGuard:
    """Every (client-rev x server-rev) cell of the negotiation matrix
    round-trips encode -> decode with field-exact equality, and the
    response codec is field-exact at every status.  This is the dynamic
    twin of fabwire's static layout comparison (tools/wire.toml codec
    serve.verify_request / serve.verify_response): a layout change that
    slips past one guard is caught by the other."""

    def test_request_matrix_every_cell_field_exact(self):
        k, s, d, _e = mixed_lanes(6)
        # the lane table is revision-independent: pin it once from the
        # current-rev body and demand identity in every cell
        ref_keys, ref_lanes, *_rest = proto.decode_verify_request(
            encode_lanes(k, s, d, version=proto.PROTOCOL_VERSION),
            version=proto.PROTOCOL_VERSION,
        )
        for client_rev in (1, 2, 3):
            for server_rev in (1, 2, 3):
                neg = min(client_rev, server_rev)
                payload = encode_lanes(
                    k, s, d, qos_class=proto.QOS_HIGH, channel="paychan",
                    deadline_ms=250, version=neg,
                )
                keys, lanes, qos, chan, dl = proto.decode_verify_request(
                    payload, version=neg
                )
                assert keys == ref_keys, f"cell ({client_rev},{server_rev})"
                assert lanes == ref_lanes, f"cell ({client_rev},{server_rev})"
                if neg >= 2:
                    assert (qos, chan) == (proto.QOS_HIGH, "paychan")
                else:
                    # v1 bodies carry no prefix: the server treats the
                    # client as unclassified traffic, never an error
                    assert (qos, chan) == (proto.DEFAULT_QOS, "")
                assert dl == (250 if neg >= 3 else 0)

    def test_request_matrix_prefix_byte_arithmetic(self):
        """The rev deltas are exactly the declared gated fields: v2
        adds the 2-byte QoS prefix + channel bytes, v3 adds the 4-byte
        deadline — nothing else moves."""
        k, s, d, _e = mixed_lanes(4)
        chan = "paychan"
        v1 = encode_lanes(k, s, d, qos_class=None, version=1)
        v2 = encode_lanes(
            k, s, d, qos_class=proto.QOS_HIGH, channel=chan, version=2
        )
        v3 = encode_lanes(
            k, s, d, qos_class=proto.QOS_HIGH, channel=chan,
            deadline_ms=250, version=3,
        )
        assert len(v2) == len(v1) + 2 + len(chan.encode())
        assert len(v3) == len(v2) + 4
        # the shared suffix (the lane table) is byte-identical
        assert v2.endswith(v1)
        assert v3.endswith(v1)

    def test_response_round_trip_field_exact_at_every_status(self):
        mask = [True, False, True, True]
        cells = [
            (proto.ST_OK, mask, "", 0),
            (proto.ST_BUSY, None, "shed: hot bucket", 40),
            (proto.ST_ERROR, None, "engine exploded", 0),
            (proto.ST_STOPPING, None, "draining", 125),
        ]
        for status, mask_in, msg, retry in cells:
            payload = proto.encode_verify_response(
                status, mask=mask_in, message=msg, retry_after_ms=retry
            )
            out = proto.decode_verify_response(payload)
            want_mask = mask if status == proto.ST_OK else None
            want_msg = "" if status == proto.ST_OK else msg
            assert out == (status, retry, want_mask, want_msg)
