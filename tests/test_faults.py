"""fabchaos foundations: the deterministic fault-injection registry
(common/faults.py) and the shared retry/backoff helper (common/retry.py).
No jax, no cryptography — pure host."""

import threading
import time

import pytest

from fabric_tpu.common import faults
from fabric_tpu.common.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    corrupt_verdicts,
    fault_point,
    plan_installed,
)
from fabric_tpu.common.retry import (
    Backoff,
    CooldownGate,
    RetryPolicy,
    call_with_retry,
)


# ---------------------------------------------------------------------------
# FaultPlan grammar + decisions
# ---------------------------------------------------------------------------


def test_parse_grammar_full():
    plan = FaultPlan.parse(
        "batcher.dispatch=raise:0.25:max=3;"
        "pipeline.commit=delay:1.0:ms=50;"
        "bccsp.verdict=corrupt:0.5:lanes=4;"
        "gossip.comm.send=drop",
        seed=9,
    )
    by_site = {s.site: s for s in plan.specs()}
    assert by_site["batcher.dispatch"].action == "raise"
    assert by_site["batcher.dispatch"].prob == 0.25
    assert by_site["batcher.dispatch"].max_fires == 3
    assert by_site["pipeline.commit"].delay_ms == 50
    assert by_site["bccsp.verdict"].lanes == 4
    assert by_site["gossip.comm.send"].prob == 1.0  # default


@pytest.mark.parametrize(
    "bad",
    [
        "no-equals-sign",
        "site=explode",  # unknown action
        "site=raise:2.0",  # prob out of range
        "site=raise:0.5:bogus=1",  # unknown param
        "site=raise:0.5:max=x",  # non-int param
    ],
)
def test_parse_rejects_malformed(bad):
    with pytest.raises((ValueError, TypeError)):
        FaultPlan.parse(bad)


def test_env_install_is_warn_never_raise(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_FAULTS", "not a plan at all")
    with pytest.warns(RuntimeWarning, match="FABRIC_TPU_FAULTS ignored"):
        faults._install_from_env()
    assert faults.active_plan() is None
    monkeypatch.setenv("FABRIC_TPU_FAULTS", "x.y=raise:0.5")
    monkeypatch.setenv("FABRIC_TPU_FAULTS_SEED", "42")
    try:
        faults._install_from_env()
        plan = faults.active_plan()
        assert plan is not None and plan.seed == 42
    finally:
        faults.clear_plan()


def test_keyed_decisions_are_call_order_independent():
    """Same (seed, site, key) -> same verdict, regardless of the order
    or thread the checks run in — the determinism contract."""
    p1 = FaultPlan.parse("s=raise:0.5", seed=13)
    p2 = FaultPlan.parse("s=raise:0.5", seed=13)
    keys = list(range(200))
    d1 = {}
    for k in keys:
        d1[k] = p1.check("s", key=k) is not None
    for k in reversed(keys):  # opposite order
        assert (p2.check("s", key=k) is not None) == d1[k]
    fired = sum(d1.values())
    assert 0 < fired < len(keys)  # ~50%: actually probabilistic


def test_seed_changes_decisions():
    a = FaultPlan.parse("s=raise:0.5", seed=1)
    b = FaultPlan.parse("s=raise:0.5", seed=2)
    da = [a.check("s", key=k) is not None for k in range(64)]
    db = [b.check("s", key=k) is not None for k in range(64)]
    assert da != db


def test_max_fires_caps_and_counts():
    plan = FaultPlan.parse("s=raise:1.0:max=3", seed=0)
    hits = sum(plan.check("s", key=i) is not None for i in range(10))
    assert hits == 3
    assert plan.fired() == {"s": 3}
    plan.reset_counters()
    assert plan.fired() == {}
    assert plan.check("s", key=0) is not None


def test_fault_point_disabled_is_none_and_free():
    faults.clear_plan()
    assert fault_point("anything", key=1) is None


def test_fault_point_raise_delay_corrupt():
    with plan_installed(FaultPlan.parse("a=raise;b=delay:1.0:ms=5;c=corrupt")):
        with pytest.raises(InjectedFault, match="injected fault at a"):
            fault_point("a")
        t0 = time.perf_counter()
        assert fault_point("b") is None  # delay is transparent
        assert time.perf_counter() - t0 >= 0.004
        spec = fault_point("c", interprets=("corrupt",))
        assert spec is not None and spec.action == "corrupt"
    # context manager cleared the plan
    assert faults.active_plan() is None
    assert fault_point("a") is None


def test_corrupt_verdicts_width():
    spec = FaultSpec("s", "corrupt", lanes=2)
    assert corrupt_verdicts([True, True, True], spec) == [False, False, True]
    all_spec = FaultSpec("s", "corrupt", lanes=0)
    assert corrupt_verdicts([True, False], all_spec) == [False, True]


def test_unkeyed_decisions_are_seed_reproducible_single_thread():
    seq = [
        FaultPlan.parse("s=raise:0.3", seed=5).check("s") is not None
        for _ in range(1)
    ]
    a = FaultPlan.parse("s=raise:0.3", seed=5)
    b = FaultPlan.parse("s=raise:0.3", seed=5)
    sa = [a.check("s") is not None for _ in range(50)]
    sb = [b.check("s") is not None for _ in range(50)]
    assert sa == sb and seq[0] == sa[0]


def test_plan_check_thread_safety_counts_exactly():
    plan = FaultPlan.parse("s=raise:1.0:max=64", seed=0)
    hits = []
    lock = threading.Lock()

    def worker():
        got = sum(plan.check("s", key=i) is not None for i in range(32))
        with lock:
            hits.append(got)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(hits) == 64  # the cap is exact under contention


# ---------------------------------------------------------------------------
# RetryPolicy / Backoff / call_with_retry / CooldownGate
# ---------------------------------------------------------------------------


def test_backoff_ramp_cap_and_deadline():
    sleeps = []
    policy = RetryPolicy(base_s=0.1, multiplier=2.0, cap_s=0.4, deadline_s=1.2)
    bo = Backoff(policy, sleeper=sleeps.append)
    while bo.sleep():
        pass
    # 0.1 + 0.2 + 0.4 + 0.4 = 1.1 <= 1.2; the next 0.4 would breach
    assert sleeps == [0.1, 0.2, 0.4, 0.4]
    assert bo.total_delay_s == pytest.approx(1.1)


def test_backoff_max_attempts_and_reset():
    sleeps = []
    policy = RetryPolicy(base_s=0.1, multiplier=2.0, cap_s=10, deadline_s=10,
                         max_attempts=2)
    bo = Backoff(policy, sleeper=sleeps.append)
    assert bo.sleep() and bo.sleep() and not bo.sleep()
    assert sleeps == [0.1, 0.2]
    bo.reset()  # success restarts the ramp, deadline budget persists
    assert bo.sleep()
    assert sleeps[-1] == 0.1


def test_backoff_jitter_seeded_deterministic():
    policy = RetryPolicy(base_s=0.1, multiplier=1.0, cap_s=1, deadline_s=10,
                         jitter=0.5, max_attempts=5)
    a, b = [], []
    boa = Backoff(policy, seed=3, sleeper=a.append)
    bob = Backoff(policy, seed=3, sleeper=b.append)
    for _ in range(5):
        boa.sleep()
        bob.sleep()
    assert a == b
    assert any(abs(x - 0.1) > 1e-9 for x in a)  # jitter actually applied
    for x in a:
        assert 0.05 - 1e-9 <= x <= 0.15 + 1e-9


def test_call_with_retry_recovers_and_respects_budget():
    calls = []

    def flaky(attempt):
        calls.append(attempt)
        if attempt < 2:
            raise InjectedFault("x")
        return "ok"

    assert (
        call_with_retry(
            flaky,
            policy=RetryPolicy(base_s=0, multiplier=1, cap_s=0, deadline_s=1,
                               max_attempts=5),
            sleeper=lambda s: None,
        )
        == "ok"
    )
    assert calls == [0, 1, 2]

    def always(attempt):
        raise InjectedFault("y")

    with pytest.raises(InjectedFault):
        call_with_retry(
            always,
            policy=RetryPolicy(base_s=0, multiplier=1, cap_s=0, deadline_s=1,
                               max_attempts=3),
            sleeper=lambda s: None,
        )


def test_call_with_retry_nontransient_propagates_immediately():
    calls = []

    def broken(attempt):
        calls.append(attempt)
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        call_with_retry(broken, sleeper=lambda s: None)
    assert calls == [0]


def test_cooldown_gate_escalates_and_resets():
    now = [0.0]
    gate = CooldownGate(
        RetryPolicy(base_s=1.0, multiplier=2.0, cap_s=8.0,
                    deadline_s=float("inf")),
        clock=lambda: now[0],
    )
    assert gate.ready()
    gate.record_failure()
    assert not gate.ready()
    now[0] = 1.0
    assert gate.ready()
    gate.record_failure()  # second failure: 2s cooldown
    now[0] = 2.0
    assert not gate.ready()
    now[0] = 3.0
    assert gate.ready()
    gate.record_success()
    gate.record_failure()  # ramp reset: back to 1s
    now[0] = 4.1
    assert gate.ready()


# ---------------------------------------------------------------------------
# seam integration: the hostec pool rebuild honors the cooldown
# ---------------------------------------------------------------------------


def test_hostec_broken_shutdown_arms_cooldown(monkeypatch):
    from fabric_tpu.crypto import hostec

    gate = hostec._POOL_GATE
    monkeypatch.setattr(gate, "_failures", 0)
    monkeypatch.setattr(gate, "_open_until", 0.0)
    hostec.shutdown_pool(broken=True)
    assert not gate.ready()
    # a clean shutdown must NOT extend the cooldown
    failures_before = gate._failures
    hostec.shutdown_pool(broken=False)
    assert gate._failures == failures_before
    monkeypatch.setattr(gate, "_open_until", 0.0)
    monkeypatch.setattr(gate, "_failures", 0)


def test_multi_spec_site_budgets_are_independent():
    """Two specs on one site each get their own max_fires budget (the
    site-wide counter would starve the second spec)."""
    plan = FaultPlan.parse("s=raise:1.0:max=2;s=corrupt:1.0:max=5", seed=0)
    raises = corrupts = 0
    for i in range(20):
        spec = plan.check("s", key=i, interprets=("corrupt",))
        if spec is None:
            continue
        if spec.action == "raise":
            raises += 1
        elif spec.action == "corrupt":
            corrupts += 1
    assert raises == 2
    assert corrupts == 5
    assert plan.fired() == {"s": 7}  # aggregated per site for scorecards


def test_plan_installed_restores_previous_plan():
    """A scoped plan (scenario runner) must restore the operator's
    process-wide plan on exit, not disarm it — the FABRIC_TPU_FAULTS +
    bench_chaos combination depends on it."""
    outer = FaultPlan.parse("deliver.pull=raise:0.5", seed=1)
    inner = FaultPlan.parse("batcher.submit=raise:1.0", seed=2)
    faults.install_plan(outer)
    try:
        with plan_installed(inner):
            assert faults.active_plan() is inner
        assert faults.active_plan() is outer
    finally:
        faults.clear_plan()
    assert faults.active_plan() is None


def test_cooldown_gate_no_overflow_after_many_failures():
    """A persistently-broken environment grows the failure count without
    bound; the exponent must clamp instead of raising OverflowError."""
    now = [0.0]
    gate = CooldownGate(clock=lambda: now[0])
    for _ in range(2000):
        gate.record_failure()
    assert not gate.ready()
    bo = Backoff(
        RetryPolicy(base_s=0.01, multiplier=2.0, cap_s=0.02,
                    deadline_s=float("inf")),
        sleeper=lambda s: None,
    )
    bo.attempts = 5000  # simulate a very long retry loop
    assert bo.next_delay() == 0.02


def test_uninterpreted_action_skipped_uncounted_with_warning():
    """A corrupt/drop spec at a site that doesn't implement it must not
    fire, not count, and must warn exactly once."""
    plan = FaultPlan.parse("pipeline.commit=drop;pipeline.commit=raise:1.0:max=1")
    with pytest.warns(RuntimeWarning, match="does not interpret 'drop'"):
        spec = plan.check("pipeline.commit", key=1)
    assert spec is not None and spec.action == "raise"  # falls through
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")  # a second warning would raise here
        assert plan.check("pipeline.commit", key=2) is None  # raise capped
    assert plan.fired() == {"pipeline.commit": 1}  # only the raise counted
    # a site that DOES interpret the action receives the spec
    plan2 = FaultPlan.parse("bccsp.verdict=corrupt:1.0")
    assert plan2.check("bccsp.verdict", interprets=("corrupt",)).action == "corrupt"
