"""Real-gRPC network e2e: orderer node + two peer nodes on localhost
ports, SDK-style client flow over the wire (reference integration/e2e
with NWO, here in-process servers on dynamic ports).

client --gRPC--> peer.ProcessProposal (simulate+endorse)
client assembles tx --gRPC--> orderer.Broadcast
peers pull blocks --gRPC--> orderer.Deliver --> commit pipeline
client observes --gRPC--> peer Deliver/DeliverFiltered
"""

import time

import pytest

pytest.importorskip(
    "cryptography", reason="gRPC network tests generate X.509 material"
)

from fabric_tpu.chaincode import ChaincodeStub, Response, success, error_response
from fabric_tpu.channelconfig import (
    ApplicationProfile,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    genesis_block,
)
from fabric_tpu.comm.server import channel_to
from fabric_tpu.comm.services import (
    broadcast_envelope,
    deliver_stream,
    process_proposal,
)
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.deliver.client import seek_envelope
from fabric_tpu.endorser import create_proposal, create_signed_tx
from fabric_tpu.endorser.txbuilder import create_signed_proposal
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.nodes import OrdererNode, PeerNode
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import common_pb2
from fabric_tpu.validation.validator import ChaincodeDefinition, ChaincodeRegistry

PROVIDER = SoftwareProvider()
CHANNEL = "grpcchannel"


class KVChaincode:
    def init(self, stub):
        return success()

    def invoke(self, stub: ChaincodeStub) -> Response:
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return success(b"ok")
        if fn == "get":
            return success(stub.get_state(params[0]) or b"")
        return error_response(f"unknown {fn}")


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("grpcnet")
    org1 = generate_org("org1.example.com", "Org1MSP")
    org2 = generate_org("org2.example.com", "Org2MSP")
    oorg = generate_org("orderer.example.com", "OrdererMSP")
    mgr = MSPManager(
        [org1.msp(provider=PROVIDER), org2.msp(provider=PROVIDER)]
    )
    policy = from_dsl("AND('Org1MSP.member','Org2MSP.member')")

    def registry_factory(channel_id):
        return ChaincodeRegistry([ChaincodeDefinition("kvcc", policy)])

    profile = Profile(
        application=ApplicationProfile(
            organizations=[
                OrganizationProfile("Org1MSP", org1.msp_config()),
                OrganizationProfile("Org2MSP", org2.msp_config()),
            ]
        ),
        orderer=OrdererProfile(
            orderer_type="solo",
            organizations=[OrganizationProfile("OrdererMSP", oorg.msp_config())],
        ),
    )
    gblock = genesis_block(profile, CHANNEL)

    orderer = OrdererNode(
        str(tmp / "orderer"), signer=SigningIdentity(oorg.peers[0], PROVIDER)
    )
    orderer.join_channel(gblock)
    orderer.start()

    peers = []
    for i, org in enumerate((org1, org2)):
        peer = PeerNode(
            str(tmp / f"peer{i}"),
            mgr,
            SigningIdentity(org.peers[0], PROVIDER),
            registry_factory,
            provider=PROVIDER,
        )
        peer.support.register("kvcc", KVChaincode())
        peer.join_channel(gblock)
        peer.start()
        peer.start_deliver_for_channel(CHANNEL, orderer.addr)
        peers.append(peer)

    yield {
        "orderer": orderer,
        "peers": peers,
        "org1": org1,
        "org2": org2,
        "client": SigningIdentity(org1.users[0], PROVIDER),
    }
    for p in peers:
        p.stop()
    orderer.stop()


def _wait_height(peers, h, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(p.channels[CHANNEL].ledger.height >= h for p in peers):
            return True
        time.sleep(0.05)
    return False


def test_grpc_end_to_end(net):
    client = net["client"]
    # 1. endorse on both peers over gRPC
    bundle = create_proposal(client, CHANNEL, "kvcc", [b"put", b"k1", b"v1"])
    signed = create_signed_proposal(bundle, client)
    responses = []
    for peer in net["peers"]:
        conn = channel_to(peer.addr)
        resp = process_proposal(conn, signed)
        conn.close()
        assert resp.response.status == 200, resp.response.message
        responses.append(resp)

    # 2. assemble + broadcast over gRPC
    env = create_signed_tx(bundle, client, responses)
    conn = channel_to(net["orderer"].addr)
    ack = broadcast_envelope(conn, env)
    conn.close()
    assert ack.status == common_pb2.SUCCESS, ack.info

    # 3. both peers commit the block via their deliver loops
    assert _wait_height(net["peers"], 2), (
        f"peers did not commit in time; deliver errors: "
        f"{[p.deliver_errors for p in net['peers']]}"
    )
    for peer in net["peers"]:
        ch = peer.channels[CHANNEL]
        assert ch.ledger.get_state("kvcc", "k1") == b"v1"
    # cross-peer state fingerprint agreement
    h0 = net["peers"][0].channels[CHANNEL].ledger.commit_hash
    h1 = net["peers"][1].channels[CHANNEL].ledger.commit_hash
    assert h0 == h1 and h0

    # 4. a follow-up query proposal sees the committed value
    qbundle = create_proposal(client, CHANNEL, "kvcc", [b"get", b"k1"])
    qsigned = create_signed_proposal(qbundle, client)
    conn = channel_to(net["peers"][1].addr)
    qresp = process_proposal(conn, qsigned)
    conn.close()
    assert qresp.response.status == 200
    assert qresp.response.payload == b"v1"


def test_grpc_peer_deliver_filtered(net):
    client = net["client"]
    peer = net["peers"][0]
    env = seek_envelope(CHANNEL, start=1, stop=1, signer=client)
    conn = channel_to(peer.addr)
    resps = list(
        deliver_stream(conn, env, service="protos.Deliver", method="DeliverFiltered")
    )
    conn.close()
    fb = [r for r in resps if r.WhichOneof("Type") == "filtered_block"]
    assert fb, resps
    assert fb[0].filtered_block.number == 1
    assert fb[0].filtered_block.filtered_transactions[0].tx_validation_code == 0


def test_grpc_qscc_via_endorser(net):
    client = net["client"]
    peer = net["peers"][0]
    bundle = create_proposal(
        client, CHANNEL, "qscc", [b"GetChainInfo", CHANNEL.encode()]
    )
    signed = create_signed_proposal(bundle, client)
    conn = channel_to(peer.addr)
    resp = process_proposal(conn, signed)
    conn.close()
    assert resp.response.status == 200, resp.response.message
    info = common_pb2.BlockchainInfo()
    info.ParseFromString(resp.response.payload)
    assert info.height >= 2


def test_grpc_broadcast_rejects_unknown_channel(net):
    client = net["client"]
    bundle = create_proposal(client, "nochannel", "kvcc", [b"put", b"x", b"y"])
    signed = create_signed_proposal(bundle, client)
    # endorsement fails on the peer (unknown channel)
    conn = channel_to(net["peers"][0].addr)
    resp = process_proposal(conn, signed)
    conn.close()
    assert resp.response.status == 500
