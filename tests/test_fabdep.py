"""fabdep — whole-program layering + concurrency analyzer.

One firing fixture per rule (import-cycle, layer-skip, layer-unknown,
unguarded-shared-write, lock-order-cycle, blocking-under-lock,
dead-export), the negative control next to each, suppression semantics
(per-line and per-edge), the mini-TOML layer map parser, CLI surfaces,
and the repo self-check: fabric_tpu/ analyzed with the shipped
tools/layers.toml must produce ZERO unsuppressed findings and a package
graph consistent with the declared layers.
"""

import json
from pathlib import Path

import pytest

from fabric_tpu.tools import fabdep
from fabric_tpu.tools.fabdep import LayerMap, analyze

REPO = Path(__file__).resolve().parent.parent


def write_tree(root: Path, files: dict) -> Path:
    for rel, body in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(body, encoding="utf-8")
    return root


def run(root: Path, layers: LayerMap = None, refs=(), rules=None):
    _program, findings = analyze(root, layers, refs, rules)
    return findings


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# pass 1: layering
# ---------------------------------------------------------------------------


def test_package_import_cycle_fires(tmp_path):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "alpha/__init__.py": "from proj.beta import core\n",
            "alpha/core.py": "",
            "beta/__init__.py": "from proj.alpha import core\n",
            "beta/core.py": "",
        },
    )
    findings = run(root)
    assert "import-cycle" in rules_of(findings)
    msg = next(f for f in findings if f.rule == "import-cycle").message
    assert "alpha" in msg and "beta" in msg  # full cycle path reported


def test_deferred_import_still_counts_for_package_cycle(tmp_path):
    # architectural cycles hide inside functions; the package pass sees them
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "alpha/__init__.py": "from proj.beta import core\n",
            "alpha/core.py": "",
            "beta/__init__.py": "",
            "beta/core.py": (
                "def f():\n    from proj.alpha import core\n    return core\n"
            ),
        },
    )
    assert "import-cycle" in rules_of(run(root))


def test_scc_that_is_not_one_simple_cycle_reports_without_crash(tmp_path):
    # A <-> B plus B <-> C: one SCC {A,B,C} whose representative path
    # has a closing pair that is NOT an import edge — the report must
    # list the sites that exist instead of raising KeyError
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "aaa/__init__.py": "from proj.bbb import x\n",
            "aaa/x.py": "",
            "bbb/__init__.py": (
                "from proj.aaa import x\nfrom proj.ccc import x as y\n"
            ),
            "bbb/x.py": "",
            "ccc/__init__.py": "from proj.bbb import x\n",
            "ccc/x.py": "",
        },
    )
    findings = run(root)
    assert "import-cycle" in rules_of(findings)


def test_no_cycle_no_finding(tmp_path):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "alpha/__init__.py": "from proj.beta import core\n",
            "alpha/core.py": "",
            "beta/__init__.py": "",
            "beta/core.py": "",
        },
    )
    assert run(root) == []


def test_layer_skip_fires_upward_only(tmp_path):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "low/__init__.py": "from proj.high import api\n",  # upward: bad
            "low/api.py": "",
            "high/__init__.py": "",
            "high/api.py": "",
            # downward import, skipping a layer: allowed
            "top/__init__.py": "from proj.low import api\n",
            "top/api.py": "",
        },
    )
    layers = LayerMap({"low": 0, "high": 1, "top": 3})
    findings = run(root, layers)
    assert rules_of(findings) == ["layer-skip"]
    assert all(f.rule != "layer-skip" or "low" in f.message for f in findings)


def test_layer_unknown_fires(tmp_path):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "low/__init__.py": "from proj.mystery import api\n",
            "low/api.py": "",
            "mystery/__init__.py": "",
            "mystery/api.py": "",
        },
    )
    findings = run(root, LayerMap({"low": 0}))
    assert "layer-unknown" in rules_of(findings)


def test_allow_edge_suppresses_layer_and_cycle(tmp_path):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "alpha/__init__.py": "from proj.beta import core\n",
            "alpha/core.py": "",
            "beta/__init__.py": "from proj.alpha import core\n",
            "beta/core.py": "",
        },
    )
    layers = LayerMap(
        {"alpha": 0, "beta": 1},
        allow={("alpha", "beta"): "historical edge, tracked in #123"},
    )
    findings = run(root, layers)
    # the allowed edge is exempt from BOTH checks; the cycle dissolves
    assert findings == []


# ---------------------------------------------------------------------------
# pass 2: concurrency
# ---------------------------------------------------------------------------

RACE_SRC = """
import threading

class Worker:
    def __init__(self):
        self.count = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            self.count += 1{thread_guard}

    def poke(self):
        self.count = 0{main_guard}
"""


def _race_tree(tmp_path, thread_guard="", main_guard="", extra=""):
    src = RACE_SRC.format(thread_guard=thread_guard, main_guard=main_guard)
    return write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src + extra},
    )


def test_unguarded_shared_write_fires(tmp_path):
    findings = run(_race_tree(tmp_path))
    assert rules_of(findings) == ["unguarded-shared-write"]
    assert any("self.count" in f.message for f in findings)


def test_guarded_shared_write_is_clean(tmp_path):
    src = """
import threading

class Worker:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            with self._lock:
                self.count += 1

    def poke(self):
        with self._lock:
            self.count = 0
"""
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    assert run(root) == []


def test_caller_held_lock_is_inherited(tmp_path):
    # the write sits in a helper whose every caller holds the lock
    src = """
import threading

class Worker:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _bump_locked(self):
        self.count += 1

    def _run(self):
        while True:
            with self._lock:
                self._bump_locked()

    def poke(self):
        with self._lock:
            self._bump_locked()
"""
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    assert run(root) == []


def test_queue_typed_state_is_exempt(tmp_path):
    src = """
import queue
import threading

class Worker:
    def __init__(self):
        self.q = queue.Queue()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            self.q.put(1)

    def poke(self):
        self.q.put(2)
"""
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    assert run(root) == []


def test_process_pool_submit_is_not_a_thread_entry(tmp_path):
    src = """
from concurrent.futures import ProcessPoolExecutor

_POOL = None
_COUNTER = 0

def _pool():
    global _POOL
    if _POOL is None:
        _POOL = ProcessPoolExecutor(2)
    return _POOL

def work():
    global _COUNTER
    _COUNTER += 1  # worker process: shares no memory with the parent
    return _COUNTER

def dispatch(items):
    pool = _pool()
    return [pool.submit(work, i) for i in items]
"""
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    assert run(root) == []


def test_lock_order_cycle_fires(tmp_path):
    src = """
import threading

lock_a = threading.Lock()
lock_b = threading.Lock()

def forward():
    with lock_a:
        with lock_b:
            pass

def backward():
    with lock_b:
        with lock_a:
            pass
"""
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    findings = run(root)
    assert rules_of(findings) == ["lock-order-cycle"]
    assert "lock_a" in findings[0].message and "lock_b" in findings[0].message


def test_consistent_lock_order_is_clean(tmp_path):
    src = """
import threading

lock_a = threading.Lock()
lock_b = threading.Lock()

def one():
    with lock_a:
        with lock_b:
            pass

def two():
    with lock_a:
        with lock_b:
            pass
"""
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    assert run(root) == []


def test_blocking_under_lock_fires(tmp_path):
    src = """
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        pass

    def stop(self):
        with self._lock:
            self._t.join()
"""
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    findings = run(root)
    assert rules_of(findings) == ["blocking-under-lock"]


def test_str_join_under_lock_is_not_blocking(tmp_path):
    src = """
import threading

_lock = threading.Lock()

def render(parts):
    with _lock:
        return ", ".join(sorted(parts))
"""
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    assert run(root) == []


# ---------------------------------------------------------------------------
# pass 3: dead exports
# ---------------------------------------------------------------------------


def test_dead_export_fires_and_external_use_is_live(tmp_path):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "pkg/__init__.py": "",
            "pkg/mod.py": (
                '__all__ = ["live_api", "dead_api"]\n'
                "def live_api():\n    pass\n"
                "def dead_api():\n    pass\n"
            ),
            "other/__init__.py": "",
            "other/consumer.py": "from proj.pkg.mod import live_api\n",
        },
    )
    findings = run(root)
    assert rules_of(findings) == ["dead-export"]
    assert "dead_api" in findings[0].message
    assert all("'live_api'" not in f.message for f in findings)


def test_dead_export_counts_reference_roots(tmp_path):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "pkg/__init__.py": "",
            "pkg/mod.py": '__all__ = ["api"]\ndef api():\n    pass\n',
        },
    )
    tests_dir = tmp_path / "exttests"
    tests_dir.mkdir()
    (tests_dir / "test_x.py").write_text("from proj.pkg.mod import api\n")
    assert run(root) != []  # dead without the ref root
    assert run(root, refs=[tests_dir]) == []  # alive with it


def test_dead_export_init_reexport_live_via_submodule(tmp_path):
    # pkg/__init__ re-exports a name; an external module imports it from
    # the SUBMODULE — the __init__ claim is still a live API surface
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "pkg/__init__.py": (
                'from proj.pkg.mod import api\n__all__ = ["api"]\n'
            ),
            "pkg/mod.py": "def api():\n    pass\n",
            "other/__init__.py": "",
            "other/consumer.py": "from proj.pkg.mod import api\n",
        },
    )
    assert run(root) == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_per_line_suppression_with_reason(tmp_path):
    src = RACE_SRC.format(
        thread_guard="  # fabdep: disable=unguarded-shared-write  # stats only",
        main_guard="  # fabdep: disable=unguarded-shared-write  # stats only",
    )
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    program, findings = analyze(root)
    assert findings == []
    assert program.suppressed >= 1


def test_disable_all_suppresses_everything_on_the_line(tmp_path):
    src = RACE_SRC.format(
        thread_guard="  # fabdep: disable=all  # measured, benign",
        main_guard="  # fabdep: disable=all  # measured, benign",
    )
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    assert run(root) == []


def test_suppressing_the_wrong_rule_does_not_silence(tmp_path):
    src = RACE_SRC.format(
        thread_guard="  # fabdep: disable=layer-skip  # wrong rule id",
        main_guard="",
    )
    root = write_tree(
        tmp_path / "proj",
        {"__init__.py": "", "pkg/__init__.py": "", "pkg/mod.py": src},
    )
    assert "unguarded-shared-write" in rules_of(run(root))


# ---------------------------------------------------------------------------
# layer map parsing
# ---------------------------------------------------------------------------


def test_layermap_parses_toml_subset():
    text = """
# comment
[layers]
protos = 0
"crypto" = 2

[allow]
"a -> b" = "grandfathered; tracked in ROADMAP"
"""
    lm = LayerMap.parse(text)
    assert lm.layers == {"protos": 0, "crypto": 2}
    assert lm.allow[("a", "b")].startswith("grandfathered")
    assert lm.allowed("a", "b") and not lm.allowed("b", "a")


def test_layermap_rejects_bad_level():
    with pytest.raises(ValueError):
        LayerMap.parse("[layers]\nprotos = zero\n")


def test_layermap_rejects_bad_allow_key():
    with pytest.raises(ValueError):
        LayerMap.parse("[allow]\nnodash = why\n")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_list_rules(capsys):
    assert fabdep.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in fabdep.RULES:
        assert rid in out


def test_cli_json_and_exit_codes(tmp_path, capsys):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "alpha/__init__.py": "from proj.beta import x\n",
            "alpha/x.py": "",
            "beta/__init__.py": "from proj.alpha import x\n",
            "beta/x.py": "",
        },
    )
    assert fabdep.main(["--json", "--no-default-refs", str(root)]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["findings"] and payload["stats"]["modules"] == 5
    assert {"rule", "path", "line", "col", "message"} <= set(
        payload["findings"][0]
    )


def test_cli_dot_and_graph_json(tmp_path, capsys):
    root = write_tree(
        tmp_path / "proj",
        {
            "__init__.py": "",
            "alpha/__init__.py": "from proj.beta import x\n",
            "alpha/x.py": "",
            "beta/__init__.py": "",
            "beta/x.py": "",
        },
    )
    assert fabdep.main(["--dot", "--no-default-refs", str(root)]) == 0
    dot = capsys.readouterr().out
    assert "digraph" in dot and '"alpha" -> "beta"' in dot
    assert fabdep.main(["--graph-json", "--no-default-refs", str(root)]) == 0
    graph = json.loads(capsys.readouterr().out)
    assert {
        "src": "alpha", "dst": "beta", "imports": 1, "deferred": 0
    } in graph["edges"]


def test_cli_usage_errors(tmp_path):
    assert fabdep.main([]) == 2
    assert fabdep.main([str(tmp_path / "missing")]) == 2
    assert fabdep.main(["--rules", "no-such-rule", str(tmp_path)]) == 2


# ---------------------------------------------------------------------------
# repo self-check
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def repo_analysis():
    root = REPO / "fabric_tpu"
    layer_file = fabdep.default_layer_file(root)
    assert layer_file is not None, "tools/layers.toml must ship with the repo"
    layer_map = LayerMap.parse(layer_file.read_text(), str(layer_file))
    refs = fabdep.default_ref_paths(root)
    program, findings = analyze(root, layer_map, refs)
    return program, findings, layer_map


def test_repo_has_zero_unsuppressed_findings(repo_analysis):
    _program, findings, _lm = repo_analysis
    pretty = "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in findings
    )
    assert findings == [], f"fabdep must stay clean:\n{pretty}"


def test_toolkit_port_changed_nothing(repo_analysis):
    """The PR 11 toolkit extraction is behavior-pinned: same chassis
    objects, same rule ids, and the repo's suppressed count exactly as
    before the port (program.suppressed_findings lists them for
    fabreg's suppression-stale rule)."""
    from fabric_tpu.tools import toolkit

    assert fabdep.Finding is toolkit.Finding
    assert fabdep.DEFAULT_EXCLUDES == toolkit.DEFAULT_EXCLUDES
    assert sorted(fabdep.RULES) == [
        "blocking-under-lock", "dead-export", "import-cycle", "layer-skip",
        "layer-unknown", "lock-order-cycle", "unguarded-shared-write",
    ]
    program, _findings, _lm = repo_analysis
    assert program.suppressed == 12
    assert len(program.suppressed_findings) == 12
    assert {f.rule for f in program.suppressed_findings} == {
        "unguarded-shared-write"
    }


def test_repo_package_graph_is_a_layered_dag(repo_analysis):
    program, _findings, layer_map = repo_analysis
    graph = fabdep.graph_dict(program, layer_map)
    # every package placed, every edge flows downward or level
    by_name = {p["name"]: p["layer"] for p in graph["packages"]}
    assert all(layer is not None for layer in by_name.values()), by_name
    for e in graph["edges"]:
        assert by_name[e["src"]] >= by_name[e["dst"]], e
    # and the seed's four cycles stay gone: acyclic edge set
    adj = {}
    for e in graph["edges"]:
        adj.setdefault(e["src"], set()).add(e["dst"])
    assert fabdep._find_cycles(adj) == []


def test_repo_suppressions_all_carry_reasons():
    # every in-tree fabdep suppression must justify itself with a
    # trailing comment, same discipline as fablint
    offenders = []
    for path in (REPO / "fabric_tpu").rglob("*.py"):
        if "__pycache__" in path.parts:
            continue
        for n, line in enumerate(path.read_text().splitlines(), start=1):
            if "# fabdep: disable=" in line:
                after = line.split("# fabdep: disable=", 1)[1]
                if "#" not in after:
                    offenders.append(f"{path}:{n}")
    assert offenders == [], offenders
