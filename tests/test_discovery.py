"""Service discovery: principal-set inquire, peers/config/endorsers
queries, auth (reference discovery/, common/policies/inquire)."""

import pytest

from conftest import requires_crypto

from fabric_tpu.channelconfig import (
    ApplicationProfile,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    genesis_block,
)
from fabric_tpu.channelconfig.bundle import bundle_from_genesis_block
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.discovery import DiscoveryService, PeerInfo, satisfied_by
from fabric_tpu.discovery.inquire import TooManyCombinationsError
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.policy import from_dsl
from fabric_tpu.policy.ast import MSPRole, Role
from fabric_tpu.policy.manager import SignedData

PROVIDER = SoftwareProvider()


# ---------------- inquire ----------------


def test_satisfied_by_and():
    sets = satisfied_by(from_dsl("AND('A.member','B.member')"))
    assert sets == [
        (MSPRole("A", Role.MEMBER), MSPRole("B", Role.MEMBER)),
    ]


def test_satisfied_by_or():
    sets = satisfied_by(from_dsl("OR('A.member','B.member')"))
    assert sets == [
        (MSPRole("A", Role.MEMBER),),
        (MSPRole("B", Role.MEMBER),),
    ]


def test_satisfied_by_nested_outof():
    sets = satisfied_by(
        from_dsl("OutOf(2,'A.member','B.member','C.member')")
    )
    assert len(sets) == 3
    assert (MSPRole("A", Role.MEMBER), MSPRole("B", Role.MEMBER)) in sets
    assert (MSPRole("A", Role.MEMBER), MSPRole("C", Role.MEMBER)) in sets
    assert (MSPRole("B", Role.MEMBER), MSPRole("C", Role.MEMBER)) in sets


def test_satisfied_by_nested_combination():
    sets = satisfied_by(
        from_dsl("AND('A.member', OR('B.member','C.member'))")
    )
    assert len(sets) == 2
    for s in sets:
        assert MSPRole("A", Role.MEMBER) in s


def test_combination_cap():
    terms = ",".join(f"'O{i}.member'" for i in range(30))
    with pytest.raises(TooManyCombinationsError):
        satisfied_by(from_dsl(f"OutOf(15,{terms})"))


# ---------------- service ----------------


@pytest.fixture(scope="module")
def world():
    org1 = generate_org("org1.example.com", "Org1MSP")
    org2 = generate_org("org2.example.com", "Org2MSP")
    oorg = generate_org("orderer.example.com", "OrdererMSP")
    profile = Profile(
        application=ApplicationProfile(
            organizations=[
                OrganizationProfile("Org1MSP", org1.msp_config()),
                OrganizationProfile("Org2MSP", org2.msp_config()),
            ]
        ),
        orderer=OrdererProfile(
            orderer_type="solo",
            addresses=["orderer0:7050"],
            organizations=[OrganizationProfile("OrdererMSP", oorg.msp_config())],
        ),
    )
    bundle = bundle_from_genesis_block(
        genesis_block(profile, "dchannel"), provider=PROVIDER
    )
    peers = [
        PeerInfo("Org1MSP", "peer0.org1:7051", 10, ("mycc",)),
        PeerInfo("Org1MSP", "peer1.org1:7051", 12, ("mycc", "other")),
        PeerInfo("Org2MSP", "peer0.org2:7051", 11, ("mycc",)),
    ]
    policy = from_dsl("AND('Org1MSP.member','Org2MSP.member')")
    svc = DiscoveryService(
        peers_provider=lambda ch: peers if ch == "dchannel" else [],
        bundle_provider=lambda ch: bundle if ch == "dchannel" else None,
        policy_provider=lambda cc, ch: policy if cc == "mycc" else None,
    )
    return {"svc": svc, "org1": org1, "org2": org2, "peers": peers}


def _client(org):
    s = SigningIdentity(org.users[0], PROVIDER)
    return SignedData(b"req", s.serialize(), s.sign(b"req"))


@requires_crypto
def test_peers_query(world):
    got = world["svc"].peers("dchannel", _client(world["org1"]))
    assert [p.endpoint for p in got] == [
        "peer0.org1:7051",
        "peer1.org1:7051",
        "peer0.org2:7051",
    ]


@requires_crypto
def test_config_query(world):
    cfg = world["svc"].config("dchannel", _client(world["org1"]))
    assert cfg["msps"] == ["OrdererMSP", "Org1MSP", "Org2MSP"]
    assert any("orderer0:7050" in eps for eps in cfg["orderers"].values())


@requires_crypto
def test_endorsers_query(world):
    desc = world["svc"].endorsers("dchannel", "mycc", _client(world["org1"]))
    assert len(desc.layouts) == 1
    layout = desc.layouts[0]
    assert sorted(layout.values()) == [1, 1]
    # groups: Org1 group has 2 peers (height-descending), Org2 group 1
    sizes = sorted(len(v) for v in desc.endorsers_by_groups.values())
    assert sizes == [1, 2]
    for members in desc.endorsers_by_groups.values():
        if len(members) == 2:
            assert members[0].ledger_height >= members[1].ledger_height


@requires_crypto
def test_endorsers_unknown_chaincode(world):
    from fabric_tpu.discovery.service import DiscoveryError

    with pytest.raises(DiscoveryError):
        world["svc"].endorsers("dchannel", "nope", _client(world["org1"]))


@requires_crypto
def test_auth_rejects_stranger(world):
    from fabric_tpu.discovery.service import DiscoveryError

    stranger = generate_org("rogue.example.com", "Org1MSP")
    with pytest.raises(DiscoveryError):
        world["svc"].peers("dchannel", _client(stranger))
    # cached denial stays denied
    with pytest.raises(DiscoveryError):
        world["svc"].peers("dchannel", _client(stranger))


@requires_crypto
def test_unknown_channel(world):
    from fabric_tpu.discovery.service import DiscoveryError

    with pytest.raises(DiscoveryError):
        world["svc"].peers("nochannel", _client(world["org1"]))
