"""SidecarRouter: bucket-aware placement, health-probe eviction on the
per-endpoint CooldownGate (one blackholed endpoint must not slow dials
to healthy ones — previously untested edge), re-verify-on-kill across
endpoints, drain, and the fail-closed degrade ladder."""

import threading
import time

import pytest

from fabric_tpu.common.retry import RetryPolicy
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.router import SidecarRouter, endpoints_from_env
from fabric_tpu.serve.server import SidecarServer

from tests.test_serve import mixed_lanes

FAST_GATE = RetryPolicy(
    base_s=0.05, multiplier=2.0, cap_s=0.5, deadline_s=float("inf")
)


def start_sidecar(path):
    srv = SidecarServer(
        str(path), engine="host", warm_ladder="off", buckets=(64, 256, 1024)
    )
    srv.warm()
    srv.start()
    return srv


@pytest.fixture
def fleet(tmp_path):
    servers = [start_sidecar(tmp_path / f"r{i}.sock") for i in range(2)]
    router = SidecarRouter(
        endpoints=[s.address for s in servers],
        sleeper=lambda s: None,
        gate_policy=FAST_GATE,
    )
    yield servers, router
    router.stop()
    for s in servers:
        s.stop()


class TestRouting:
    def test_batches_spread_and_masks_exact(self, fleet):
        servers, router = fleet
        for n in (48, 200, 900):
            k, s, d, e = mixed_lanes(n)
            assert list(router.batch_verify(k, s, d)) == e
        assert not router.degraded
        assert sum(s.stats.summary()["requests"] for s in servers) == 3

    def test_placement_is_stable_per_bucket(self, fleet):
        _servers, router = fleet
        first = router._order(48)
        again = router._order(48)
        assert [e.address for e in first] == [e.address for e in again]

    def test_async_resolves_through_fleet(self, fleet):
        _servers, router = fleet
        k, s, d, e = mixed_lanes(64)
        resolver = router.batch_verify_async(k, s, d)
        assert list(resolver()) == e

    def test_for_channel_binds_class_and_shares_endpoints(
        self, fleet, monkeypatch
    ):
        _servers, router = fleet
        assert router.for_channel(router.channel) is router
        monkeypatch.setenv("FABRIC_TPU_SERVE_QOS", "paychan=high;*=bulk")
        bound = router.for_channel("paychan")
        assert bound.qos_class == proto.QOS_HIGH
        assert bound.endpoints is router.endpoints  # one fleet, shared

    def test_endpoints_from_env(self, monkeypatch):
        monkeypatch.setenv(
            "FABRIC_TPU_SERVE_ENDPOINTS", " /a.sock , 127.0.0.1:9 ,"
        )
        assert endpoints_from_env() == ["/a.sock", "127.0.0.1:9"]
        with pytest.raises(ValueError):
            SidecarRouter(endpoints=[])


class TestFailover:
    def test_kill_one_reverifies_on_survivor(self, fleet):
        servers, router = fleet
        k, s, d, e = mixed_lanes(128)
        assert list(router.batch_verify(k, s, d)) == e
        victim = router._order(128)[0]
        next(srv for srv in servers if srv.address == victim.address).stop()
        k2, s2, d2, e2 = mixed_lanes(128, seed=2)
        assert list(router.batch_verify(k2, s2, d2)) == e2
        assert not router.degraded  # the survivor served it
        assert not victim.healthy  # and the dead endpoint was evicted

    def test_blackholed_endpoint_does_not_slow_healthy_dials(self, fleet):
        """The CooldownGate-reuse satellite: after ONE slow dial
        failure the blackholed endpoint is skipped without a dial for
        the whole cooldown — subsequent batches pay zero blackhole
        latency.  Uses a production-scale cooldown (a fast test gate
        would legitimately re-probe mid-test)."""
        servers, router_fast = fleet
        router = SidecarRouter(
            endpoints=[s.address for s in servers],
            sleeper=lambda s: None,
            gate_policy=RetryPolicy(
                base_s=30.0, multiplier=2.0, cap_s=60.0,
                deadline_s=float("inf"),
            ),
        )
        try:
            black = router.endpoints[0]
            dials = []

            def slow_dead_connect():
                dials.append(time.monotonic())
                time.sleep(0.25)  # a SYN blackhole, miniaturized
                raise OSError("blackholed")

            black.client.close()
            black.client._connect = slow_dead_connect
            # force one attempt at the blackholed endpoint: pays the
            # slow dial once, marks it down
            k, s, d, _e = mixed_lanes(32)
            outcome, _ = router._try_endpoint(black, k, s, d, 0)
            assert outcome == "dead" and len(dials) == 1
            # healthy traffic: gate-open endpoint skipped with NO dial
            for seed in range(4):
                k2, s2, d2, e2 = mixed_lanes(64, seed=seed)
                assert list(router.batch_verify(k2, s2, d2)) == e2
            assert len(dials) == 1, "blackholed endpoint was re-dialed"
            assert not router.degraded
        finally:
            router.stop()

    def test_all_endpoints_dead_degrades_bit_exact(self, tmp_path):
        servers = [start_sidecar(tmp_path / f"d{i}.sock") for i in range(2)]
        router = SidecarRouter(
            endpoints=[s.address for s in servers],
            sleeper=lambda s: None,
            gate_policy=FAST_GATE,
        )
        try:
            for s in servers:
                s.stop()
            k, sg, d, e = mixed_lanes(64)
            assert list(router.batch_verify(k, sg, d)) == e
            assert router.degraded  # in-process ladder served it
        finally:
            router.stop()

    def test_double_fault_fails_closed_all_false(self, tmp_path):
        class Exploding:
            def batch_verify(self, keys, sigs, digests):
                raise RuntimeError("fallback broken too")

        router = SidecarRouter(
            endpoints=[str(tmp_path / "never.sock")],
            fallback=Exploding(),
            sleeper=lambda s: None,
            gate_policy=FAST_GATE,
        )
        try:
            k, s, d, _e = mixed_lanes(12)
            assert list(router.batch_verify(k, s, d)) == [False] * 12
        finally:
            router.stop()

    def test_stopping_endpoint_reroutes(self, fleet):
        """ST_STOPPING from a draining endpoint is never trusted as a
        settlement: the batch re-verifies on the next endpoint."""
        servers, router = fleet
        preferred = router._order(64)[0]
        draining = next(
            srv for srv in servers if srv.address == preferred.address
        )
        with draining._drain_cv:
            draining._draining = True
        k, s, d, e = mixed_lanes(64, seed=5)
        assert list(router.batch_verify(k, s, d)) == e
        assert not router.degraded

    def test_recovery_after_restart(self, fleet, tmp_path):
        servers, router = fleet
        victim_ep = router._order(64)[0]  # preferred: WILL be attempted
        victim = next(
            srv for srv in servers if srv.address == victim_ep.address
        )
        victim.stop()
        k, s, d, e = mixed_lanes(64)
        assert list(router.batch_verify(k, s, d)) == e  # survivor serves
        assert not victim_ep.healthy
        servers[servers.index(victim)] = start_sidecar(victim_ep.address)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if victim_ep.gate.ready() and router._probe_ok(victim_ep):
                break
            time.sleep(0.02)
        assert victim_ep.healthy, "restarted endpoint never re-probed up"

    def test_drain_endpoint_acks_and_evicts(self, fleet):
        servers, router = fleet
        addr = router.endpoints[0].address
        assert router.drain_endpoint(addr)
        assert not router.endpoints[0].healthy
        target = next(srv for srv in servers if srv.address == addr)
        deadline = time.monotonic() + 5.0
        while not target._stopping and time.monotonic() < deadline:
            time.sleep(0.02)
        assert target._stopping


class TestFactoryWiring:
    def test_env_endpoints_build_router(self, fleet, monkeypatch):
        servers, _router = fleet
        from fabric_tpu.crypto.factory import provider_from_config

        monkeypatch.setenv(
            "FABRIC_TPU_SERVE_ENDPOINTS",
            ",".join(s.address for s in servers),
        )
        provider = provider_from_config({"Default": "SERVE", "SERVE": {}})
        try:
            assert isinstance(provider, SidecarRouter)
            k, s, d, e = mixed_lanes(32)
            assert list(provider.batch_verify(k, s, d)) == e
        finally:
            provider.stop()

    def test_config_endpoints_and_qos(self, fleet):
        servers, _router = fleet
        from fabric_tpu.crypto.factory import provider_from_config

        provider = provider_from_config(
            {
                "Default": "SERVE",
                "SERVE": {
                    "Endpoints": [s.address for s in servers],
                    "QoS": "high",
                    "Channel": "paychan",
                },
            }
        )
        try:
            assert isinstance(provider, SidecarRouter)
            assert provider.qos_class == proto.QOS_HIGH
            assert provider.channel == "paychan"
        finally:
            provider.stop()
