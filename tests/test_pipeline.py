"""Two-stage commit pipeline (SURVEY §2.13 P4): prepared blocks commit
in order with device verification overlapped on the submitter thread."""

import threading
import time

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.peer.channel import Channel
from fabric_tpu.peer.pipeline import CommitPipeline
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import protoutil
from fabric_tpu.validation.validator import (
    ChaincodeDefinition,
    ChaincodeRegistry,
)

PROVIDER = SoftwareProvider()
CHANNEL = "pipechan"


@pytest.fixture(scope="module")
def world():
    org = generate_org("org1.example.com", "Org1MSP")
    mgr = MSPManager([org.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("cc", from_dsl("OR('Org1MSP.member')"))]
    )
    return {
        "mgr": mgr,
        "registry": registry,
        "client": SigningIdentity(org.users[0], PROVIDER),
        "peer": SigningIdentity(org.peers[0], PROVIDER),
    }


def _tx(world, key):
    bundle = create_proposal(world["client"], CHANNEL, "cc", [b"put", key])
    results = serialize_tx_rwset(
        rw.TxRwSet(
            (rw.NsRwSet("cc", (), (rw.KVWrite(key.decode(), False, b"v"),)),)
        )
    )
    responses = [endorse_proposal(bundle, world["peer"], results)]
    return create_signed_tx(bundle, world["client"], responses)


def _chain(world, n_blocks, txs_per_block=3):
    blocks = []
    prev = b""
    for num in range(n_blocks):
        block = protoutil.new_block(num, prev)
        for i in range(txs_per_block):
            block.data.data.append(
                _tx(world, f"b{num}k{i}".encode()).SerializeToString()
            )
        protoutil.seal_block(block)
        prev = protoutil.block_header_hash(block.header)
        blocks.append(block)
    return blocks


def test_pipeline_commits_in_order_with_overlap(tmp_path, world):
    ch = Channel(
        CHANNEL,
        str(tmp_path),
        world["mgr"],
        world["registry"],
        PROVIDER,
    )
    blocks = _chain(world, 4)

    events = []
    commits = []
    orig_store = ch.store_block

    def slow_store(block, prepared=None):
        events.append(("commit_start", block.header.number, time.monotonic()))
        time.sleep(0.15)  # make the sequential stage visibly slow
        out = orig_store(block, prepared=prepared)
        events.append(("commit_end", block.header.number, time.monotonic()))
        return out

    ch.store_block = slow_store
    orig_prepare = ch.prepare_block

    def traced_prepare(block):
        events.append(("prepare_start", block.header.number, time.monotonic()))
        return orig_prepare(block)

    ch.prepare_block = traced_prepare

    pipe = CommitPipeline(
        ch, on_commit=lambda b, f: commits.append(b.header.number)
    )
    try:
        for b in blocks:
            pipe.submit(b)
        assert pipe.drain(timeout=60)
    finally:
        pipe.stop()

    assert commits == [0, 1, 2, 3]
    assert ch.ledger.height == 4
    assert ch.ledger.get_state("cc", "b3k2") == b"v"
    # overlap: block 2's prepare started before block 1's commit finished
    t_prep2 = next(t for k, n, t in events if k == "prepare_start" and n == 2)
    t_end1 = next(t for k, n, t in events if k == "commit_end" and n == 1)
    assert t_prep2 < t_end1, events


def _chain_for_channel(world, channel_id, n_blocks, txs_per_block=3):
    """Like _chain but for an arbitrary channel id, with one corrupted
    creator signature per block so the expected mask is NOT all-VALID —
    a race that flips a lane must show up as a byte difference."""
    blocks = []
    prev = b""
    for num in range(n_blocks):
        block = protoutil.new_block(num, prev)
        for i in range(txs_per_block):
            bundle = create_proposal(
                world["client"], channel_id, "cc", [b"put", f"b{num}k{i}".encode()]
            )
            results = serialize_tx_rwset(
                rw.TxRwSet(
                    (
                        rw.NsRwSet(
                            "cc",
                            (),
                            (rw.KVWrite(f"{channel_id}b{num}k{i}", False, b"v"),),
                        ),
                    )
                )
            )
            responses = [endorse_proposal(bundle, world["peer"], results)]
            env = create_signed_tx(bundle, world["client"], responses)
            if i == txs_per_block - 1:
                # corrupt the creator signature -> BAD_CREATOR_SIGNATURE
                env.signature = bytes(env.signature[:-1]) + bytes(
                    [env.signature[-1] ^ 0xFF]
                )
            block.data.data.append(env.SerializeToString())
        protoutil.seal_block(block)
        prev = protoutil.block_header_hash(block.header)
        blocks.append(block)
    return blocks


def test_pipeline_8_threads_mask_bitexact_vs_serial(tmp_path, world):
    """Hammer the commit machinery from 8 pipelines on 8 threads at once
    (shared provider, shared MSP manager, shared hostec tables/pool) and
    require every channel's TRANSACTIONS_FILTER to match a single-threaded
    reference byte for byte.  This is the regression test for the
    stage-A/stage-B shared state audited in PR 3 (validator ident-cache
    lock, provider factory lock, hostec table lock): any cross-thread
    interference that flips a lane breaks the mask equality."""
    n_threads, n_blocks = 8, 5
    chains = {
        f"hammer{t}": _chain_for_channel(world, f"hammer{t}", n_blocks)
        for t in range(n_threads)
    }

    def fresh_channel(channel_id, root):
        return Channel(
            channel_id,
            str(root),
            world["mgr"],
            world["registry"],
            PROVIDER,
        )

    # serial reference: one channel at a time, direct store_block
    reference = {}
    for cid, blocks in chains.items():
        ch = fresh_channel(cid, tmp_path / f"serial-{cid}")
        flags = []
        for b in blocks:
            # store_block mutates block metadata; keep the originals
            # pristine for the parallel run
            copy = protoutil.new_block(0, b"")
            copy.CopyFrom(b)
            flags.append(ch.store_block(copy).tobytes())
        reference[cid] = flags

    # parallel run: 8 pipelines, one submitter thread per channel, all
    # released together
    results = {cid: [] for cid in chains}
    errors = []
    barrier = threading.Barrier(n_threads)

    def drive(cid, blocks, pipe):
        try:
            barrier.wait(timeout=30)
            for b in blocks:
                pipe.submit(b)
        except Exception as exc:  # noqa: BLE001 - surfaced via errors
            errors.append((cid, repr(exc)))

    pipes = {}
    threads = []
    try:
        for cid, blocks in chains.items():
            ch = fresh_channel(cid, tmp_path / f"par-{cid}")
            pipes[cid] = CommitPipeline(
                ch,
                on_commit=lambda b, f, cid=cid: results[cid].append(
                    f.tobytes()
                ),
                on_error=lambda b, exc, cid=cid: errors.append(
                    (cid, repr(exc))
                ),
            )
        for cid, blocks in chains.items():
            t = threading.Thread(
                target=drive, args=(cid, blocks, pipes[cid]), daemon=True
            )
            threads.append(t)
            t.start()
        for t in threads:
            t.join(timeout=120)
        for pipe in pipes.values():
            assert pipe.drain(timeout=120)
    finally:
        for pipe in pipes.values():
            pipe.stop()

    assert not errors, errors
    for cid in chains:
        assert len(results[cid]) == n_blocks, (cid, len(results[cid]))
        assert results[cid] == reference[cid], (
            f"{cid}: pipelined mask diverged from the serial reference"
        )
        # the corrupted lane really is invalid in the reference
        assert any(bytes(f) != b"\x00" * 3 for f in reference[cid])


def test_pipeline_submit_after_stop_raises_fast(tmp_path, world):
    """A full queue + a stopped committer must not deadlock submit
    (the bounded-put fix in pipeline.submit)."""
    ch = Channel(
        CHANNEL, str(tmp_path), world["mgr"], world["registry"], PROVIDER
    )
    blocks = _chain(world, 1)
    pipe = CommitPipeline(ch)
    pipe.stop()
    with pytest.raises(Exception, match="stopped"):
        pipe.submit(blocks[0])


def test_pipeline_surfaces_commit_errors(tmp_path, world):
    ch = Channel(
        CHANNEL,
        str(tmp_path),
        world["mgr"],
        world["registry"],
        PROVIDER,
    )
    blocks = _chain(world, 2)
    errors = []
    pipe = CommitPipeline(
        ch, on_error=lambda b, exc: errors.append((b.header.number, str(exc)))
    )
    try:
        pipe.submit(blocks[0])
        # out-of-order submission: block 0 again -> block store rejects
        pipe.submit(blocks[0])
        assert pipe.drain(timeout=30)
    finally:
        pipe.stop()
    assert ch.ledger.height == 1
    assert errors and errors[0][0] == 0


def test_drain_false_surfaces_last_error(tmp_path, world):
    """Satellite regression: a commit-loop failure must be recorded on
    the pipeline (last_error) so a soak run that sees drain() == False
    can tell 'slow' from 'dead' — pre-fix, the terminal exception was
    visible only to the optional on_error callback."""
    ch = Channel(
        CHANNEL, str(tmp_path), world["mgr"], world["registry"], PROVIDER
    )
    blocks = _chain(world, 1)
    pipe = CommitPipeline(ch)
    try:
        assert pipe.last_error is None and not pipe.dead
        pipe.submit(blocks[0])
        pipe.submit(blocks[0])  # duplicate -> block store rejects
        assert pipe.drain(timeout=30)
        assert pipe.last_error is not None
        assert not pipe.dead  # the loop survived: slow/erroring, not dead
    finally:
        pipe.stop()


class _AsyncLadderProvider(SoftwareProvider):
    """Provider with the async dispatch seam (device kernels, pool
    shards, the serve sidecar): records dispatch/resolve ordering so
    the tests can see prepare dispatching without waiting."""

    def __init__(self):
        super().__init__()
        self.dispatched = 0
        self.resolved = 0

    def batch_verify_async(self, keys, sigs, digests):
        out = SoftwareProvider.batch_verify(self, keys, sigs, digests)
        self.dispatched += 1

        def resolve():
            self.resolved += 1
            return out

        return resolve


def test_channel_prepare_dispatches_async_and_store_resolves(
    tmp_path, world
):
    """Channel.prepare_block must NOT wait on a provider that exposes
    batch_verify_async: the resolver rides the prepared tuple and
    store_block collects the verdicts at stage B, so block N's
    signature math overlaps block N-1's commit epilogue across the
    whole dispatch ladder (serve sidecar included)."""
    prov = _AsyncLadderProvider()
    ch = Channel(
        CHANNEL, str(tmp_path), world["mgr"], world["registry"], prov
    )
    block = _chain(world, 1)[0]
    prepared = ch.prepare_block(block)
    assert prov.dispatched == 1 and prov.resolved == 0, (
        "prepare_block resolved the async dispatch instead of deferring"
    )
    assert callable(prepared[3]), "resolver did not ride the prepared tuple"
    flags = ch.store_block(block, prepared=prepared)
    assert prov.resolved == 1
    assert ch.ledger.height == 1
    assert bytes(flags) == b"\x00" * 3, "async-prepared masks not VALID"


def test_channel_async_resolver_failure_fails_closed(tmp_path, world):
    """A resolver that dies at stage B (sidecar lost mid-batch AND the
    client shim's own degrade failed too) must surface through the
    commit error path: the block is NOT committed — fail closed,
    never fail open."""

    class _DyingProvider(SoftwareProvider):
        def batch_verify_async(self, keys, sigs, digests):
            def resolve():
                raise RuntimeError("dispatch lost")

            return resolve

    ch = Channel(
        CHANNEL, str(tmp_path), world["mgr"], world["registry"],
        _DyingProvider(),
    )
    block = _chain(world, 1)[0]
    prepared = ch.prepare_block(block)
    with pytest.raises(RuntimeError, match="dispatch lost"):
        ch.store_block(block, prepared=prepared)
    assert ch.ledger.height == 0

    # and through the two-stage pipeline: on_error sees it, no commit
    errors = []
    pipe = CommitPipeline(
        ch, on_error=lambda b, exc: errors.append(str(exc))
    )
    try:
        pipe.submit(block)
        assert pipe.drain(timeout=30)
    finally:
        pipe.stop()
    assert errors and "dispatch lost" in errors[0]
    assert ch.ledger.height == 0
