"""Adversarial raft simulation: randomized message loss/duplication/
reordering, network partitions, crash-restarts through the real WAL +
snapshot files, and log compaction — asserting the safety properties the
reference trusts etcd/raft for (etcdraft chain) and exercising recovery
the way integration/raft does with process kills.

Properties checked continuously:
  S1 (state-machine safety): if two nodes apply an entry at the same
      index, it is the same entry.
  S2 (election safety): at most one leader per term.
And at the end, after healing the network:
  L1 (convergence): every node applies the same log.
  L2 (liveness): a fresh proposal commits on every node.
"""

import os
import random

import pytest

from fabric_tpu.orderer.raft import (
    ENTRY_NORMAL,
    Entry,
    RaftNode,
    SnapshotFile,
    WAL,
)


class SimNode:
    """RaftNode + real WAL/snapshot persistence + apply loop, mirroring
    RaftChain's _pump/_recover without the block semantics."""

    def __init__(self, node_id, peers, base_dir, seed):
        self.id = node_id
        self.peers = peers
        self.dir = os.path.join(base_dir, f"n{node_id}")
        self.wal = WAL(os.path.join(self.dir, "wal.log"))
        self.snap = SnapshotFile(os.path.join(self.dir, "snapshot"))
        self.seed = seed
        self.applied = {}  # index -> data
        self.applied_index = 0
        self._boot()

    def _boot(self):
        self.node = RaftNode(
            self.id, self.peers, rng=random.Random(self.seed)
        )
        snap = self.snap.load()
        if snap is not None:
            index, term, data = snap
            self.node.snap_index = index
            self.node.snap_term = term
            self.node.snap_data = data
            self.node.commit_index = index
            self.applied_index = index
        hard, entries = self.wal.replay()
        self.node.term, self.node.voted_for = max(
            (self.node.term, self.node.voted_for), hard
        )
        for e in entries:
            if e.index > self.node.snap_index:
                self.node.log.append(e)
        self._persisted_snap = self.node.snap_index

    def crash_restart(self):
        """Lose all volatile state (outbox, role, applied map above the
        snapshot); keep only what the WAL + snapshot file carry."""
        self.wal.close()
        survived = {
            i: d for i, d in self.applied.items() if i <= self._persisted_snap
        }
        self.applied = survived
        self.applied_index = 0
        self._boot()

    def pump(self):
        """RaftChain._pump: persist, apply committed, emit messages."""
        msgs, hard, new_entries = self.node.ready()
        if hard is not None or new_entries:
            self.wal.save(hard, new_entries)
        if (
            self.node.applied_snapshot is not None
            and self.node.snap_index > self._persisted_snap
        ):
            self.snap.save(
                self.node.snap_index, self.node.snap_term, self.node.snap_data
            )
            self._persisted_snap = self.node.snap_index
            self.wal.rotate(
                (self.node.term, self.node.voted_for), self.node.log
            )
        self._apply_committed()
        return msgs

    def _apply_committed(self):
        n = self.node
        while self.applied_index < n.commit_index:
            idx = self.applied_index + 1
            # idx == snap_index: _term_at answers snap_term but the entry
            # is not in the log — snapshot jump, never log[-1]
            if idx <= n.snap_index or n._term_at(idx) is None:
                # below log start: content arrived via snapshot
                self.applied_index = n.snap_index
                continue
            e = n.log[idx - n.snap_index - 1]
            if e.type == ENTRY_NORMAL and e.data:
                self.applied[idx] = e.data
            self.applied_index = idx

    def compact(self):
        if self.applied_index > self.node.snap_index:
            self.node.compact(self.applied_index, b"snap")
            self.snap.save(
                self.node.snap_index, self.node.snap_term, b"snap"
            )
            self._persisted_snap = self.node.snap_index
            self.wal.rotate(
                (self.node.term, self.node.voted_for), self.node.log
            )


class Cluster:
    def __init__(self, n, base_dir, rng):
        self.rng = rng
        peers = list(range(1, n + 1))
        self.nodes = {
            i: SimNode(i, peers, base_dir, seed=rng.randrange(2**31))
            for i in peers
        }
        self.inflight = []  # Message list
        self.cut = set()  # (frm, to) pairs currently partitioned
        self.committed_data = {}  # S1 reference: index -> data
        self.leaders_by_term = {}  # S2: term -> leader id
        self.proposed = 0

    # -- checks ----------------------------------------------------------
    def check_safety(self):
        for node in self.nodes.values():
            if node.node.role == "leader":
                term = node.node.term
                seen = self.leaders_by_term.setdefault(term, node.id)
                assert seen == node.id, (
                    f"S2 violated: term {term} has leaders {seen} and {node.id}"
                )
            for idx, data in node.applied.items():
                ref = self.committed_data.setdefault(idx, data)
                assert ref == data, (
                    f"S1 violated: index {idx} applied as {ref!r} on one "
                    f"node and {data!r} on node {node.id}"
                )

    # -- event steps ------------------------------------------------------
    def pump_all(self):
        for node in self.nodes.values():
            for m in node.pump():
                if (m.frm, m.to) not in self.cut:
                    self.inflight.append(m)

    def deliver_one(self):
        if not self.inflight:
            return
        i = self.rng.randrange(len(self.inflight))  # reordering
        m = self.inflight.pop(i)
        if self.rng.random() < 0.05:
            return  # drop
        if self.rng.random() < 0.05:
            self.inflight.append(m)  # duplicate
        if (m.frm, m.to) in self.cut:
            return
        self.nodes[m.to].node.step(m)

    def step(self):
        roll = self.rng.random()
        if roll < 0.50:
            self.deliver_one()
        elif roll < 0.80:
            self.nodes[self.rng.randrange(1, len(self.nodes) + 1)].node.tick()
        elif roll < 0.90:
            leaders = [
                n for n in self.nodes.values() if n.node.role == "leader"
            ]
            if leaders:
                self.proposed += 1
                leaders[0].node.propose(b"cmd-%d" % self.proposed)
        elif roll < 0.94:
            node = self.nodes[self.rng.randrange(1, len(self.nodes) + 1)]
            node.crash_restart()
        elif roll < 0.97:
            node = self.nodes[self.rng.randrange(1, len(self.nodes) + 1)]
            node.compact()
        else:
            self._flip_partition()
        self.pump_all()
        self.check_safety()

    def _flip_partition(self):
        if self.cut:
            self.cut = set()
            return
        victim = self.rng.randrange(1, len(self.nodes) + 1)
        self.cut = {
            (a, b)
            for a in self.nodes
            for b in self.nodes
            if (a == victim) != (b == victim)
        }

    # -- healing + convergence --------------------------------------------
    def run_to_convergence(self, max_rounds=6000):
        self.cut = set()
        for _ in range(max_rounds):
            while self.inflight:
                m = self.inflight.pop(0)
                self.nodes[m.to].node.step(m)
                self.pump_all()
            self.check_safety()
            # checked after the drain, before ticking: a leader heartbeats
            # every tick, so inflight is never empty right after a tick
            commits = {n.node.commit_index for n in self.nodes.values()}
            applied = {n.applied_index for n in self.nodes.values()}
            if (
                len(commits) == 1
                and len(applied) == 1
                and any(n.node.role == "leader" for n in self.nodes.values())
            ):
                return
            for node in self.nodes.values():
                node.node.tick()
            self.pump_all()
        raise AssertionError(
            "no convergence: commits="
            + str({i: n.node.commit_index for i, n in self.nodes.items()})
        )


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_raft_survives_adversarial_network(tmp_path, seed):
    rng = random.Random(seed)
    cluster = Cluster(3, str(tmp_path / f"s{seed}"), rng)
    for _ in range(700):
        cluster.step()
    cluster.run_to_convergence()

    # L1: identical applied logs everywhere (above each node's snapshot
    # horizon the maps must agree; the union must be gap-free)
    logs = [n.applied for n in cluster.nodes.values()]
    union = {}
    for log in logs:
        for idx, data in log.items():
            assert union.setdefault(idx, data) == data
    top = max(n.applied_index for n in cluster.nodes.values())

    # L2: one more proposal commits everywhere after the chaos
    leader = [
        n for n in cluster.nodes.values() if n.node.role == "leader"
    ][0]
    assert leader.node.propose(b"final")
    for _ in range(200):
        cluster.pump_all()
        while cluster.inflight:
            m = cluster.inflight.pop(0)
            cluster.nodes[m.to].node.step(m)
            cluster.pump_all()
        if all(
            n.applied.get(n.applied_index) == b"final"
            or b"final" in n.applied.values()
            for n in cluster.nodes.values()
        ):
            break
        for n in cluster.nodes.values():
            n.node.tick()
    for n in cluster.nodes.values():
        assert b"final" in n.applied.values(), (
            f"node {n.id} missed the post-chaos proposal "
            f"(applied to {n.applied_index}, commit {n.node.commit_index}, "
            f"top {top})"
        )
