"""fabchaos scenario harness: determinism of the scorecard, the mask
bit-exactness/fail-closed assertions of every scenario, and the CLI.
Runs without cryptography (the validation plane rides the fake MSP)."""

import json

import pytest

from fabric_tpu.common import faults
from fabric_tpu.tools import fabchaos
from fabric_tpu.tools.fabchaos import (
    SCENARIOS,
    SMOKE,
    ChaosAssertionError,
    LanePool,
    StageClock,
    run_scenarios,
)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    assert faults.active_plan() is None, "a scenario leaked its fault plan"


# idemix_storm spends ~45s of host-bignum world building per fresh
# seed (scheme-oracle signing) even at scale 0.5 — slow-marked so
# tier-1 keeps the budget; idemix mask parity stays covered there by
# tests/test_hostbn.py's flavor differentials.  crash_matrix spawns
# ~16 subprocess peers (~10s/run); its one-site canary crash_single
# stays in tier-1 (plus tests/test_crash.py's full-matrix slow test).
_HEAVY = {"idemix_storm", "crash_matrix"}
BOUNDED = [
    pytest.param(n, marks=pytest.mark.slow) if n in _HEAVY else n
    for n in SCENARIOS
    if n != "soak"
]


@pytest.mark.parametrize("name", BOUNDED)
def test_scenario_passes_and_det_is_reproducible(name):
    """Every bounded scenario runs green twice with identical
    deterministic sections — the per-scenario core of the
    --scenario all determinism gate."""
    if name == "pool_chaos":
        pytest.skip("runs in test_pool_chaos_degrades_inline (slow pool boot)")
    det1, _ = SCENARIOS[name](11, StageClock(), 0.5)
    det2, _ = SCENARIOS[name](11, StageClock(), 0.5)
    assert det1 == det2
    det3, _ = SCENARIOS[name](12, StageClock(), 0.5)
    # a different seed must actually steer the workload (flags/masks/
    # fault sets move); static config fields may coincide
    assert det1.keys() == det3.keys()


@pytest.mark.slow
def test_pool_chaos_degrades_inline():
    det, obs = SCENARIOS["pool_chaos"](11, StageClock(), 1.0)
    assert det["mask_ok"] and det["degrade_inline_ok"]
    assert obs["faults_fired"].get("hostec.pool.submit", 0) + obs[
        "faults_fired"
    ].get("hostec_np.pool.submit", 0) >= 1


def test_run_scenarios_card_shape_and_ok():
    card = run_scenarios(["verify_faults", "commit_storm"], seed=5, scale=0.5)
    det = card["deterministic"]
    assert det["ok"] is True
    assert set(det["scenarios"]) == {"verify_faults", "commit_storm"}
    assert det["scenarios"]["verify_faults"]["mask_ok"] is True
    # observed carries stage latency summaries with p50/p99
    stages = card["observed"]["stages"]["verify_faults"]
    assert any("p99_ms" in s for s in stages.values())


def test_failed_assertion_lands_in_card_not_raise(monkeypatch):
    def exploding(seed, clock, scale=1.0):
        raise ChaosAssertionError("deterministic failure message")

    monkeypatch.setitem(SCENARIOS, "exploding", exploding)
    card = run_scenarios(["exploding"], seed=1)
    det = card["deterministic"]
    assert det["ok"] is False
    assert det["scenarios"]["exploding"] == {
        "ok": False,
        "assertion": "deterministic failure message",
    }


def test_lane_pool_ground_truth_vs_software_provider():
    """The by-construction expected verdicts agree with the real
    SoftwareProvider batch path on every corruption kind."""
    import random

    from fabric_tpu.crypto.bccsp import SoftwareProvider

    rng = random.Random(99)
    pool = LanePool(rng, n_keys=2, n_msgs=6)
    keys, sigs, digests, expected, kinds = pool.lanes(rng, 48)
    assert set(kinds) == set(fabchaos.LANE_KINDS)  # every kind sampled
    out = SoftwareProvider().batch_verify(keys, sigs, digests)
    assert list(out) == expected


def test_corrupt_detect_scenario_catches_blindness():
    det, _ = SCENARIOS["corrupt_detect"](3, StageClock())
    assert det["corruption_detected"] and det["clean_after_uninstall"]


@pytest.mark.slow
def test_idemix_storm_flavors_and_verdict_gate():
    """The idemix slice: every adversarial flavor present, the batch
    rung's mask matched the scheme oracle (a mismatch would have been
    a ChaosAssertionError), and the idemix.verdict corrupt seam was
    caught by the same gate.  Seed 11 shares the reproducibility
    test's cached world (both are slow-marked together: without the
    scenario test the world cache is cold here and the build cost just
    moves)."""
    det, obs = SCENARIOS["idemix_storm"](11, StageClock(), 0.5)
    assert det["backend"] in ("hostbn", "scheme")
    assert {
        "bad_challenge",
        "corrupted_proof_scalar",
        "wrong_attribute_commitment",
        "off_group_point",
        "identity_abar",
        "identity_aprime",
    } <= set(det["flavors"])
    assert 0 < det["valid_lanes"] < det["lanes"]
    assert det["corruption_detected"] and det["clean_after_uninstall"]
    assert obs["faults_fired"].get("idemix.verdict", 0) >= 1


def test_cli_smoke_stdout_is_deterministic(capsys):
    rc1 = fabchaos.main(
        ["--seed", "5", "--scenario", "commit_storm,deliver_flap", "--quiet"]
    )
    out1 = capsys.readouterr().out
    rc2 = fabchaos.main(
        ["--seed", "5", "--scenario", "commit_storm,deliver_flap", "--quiet"]
    )
    out2 = capsys.readouterr().out
    assert rc1 == rc2 == 0
    assert out1 == out2
    card = json.loads(out1)
    assert card["ok"] is True and card["seed"] == 5
    # stdout is the deterministic section ONLY: no wall-clock leaks
    assert "stages" not in out1 and "wall_s" not in out1


def test_cli_out_file_carries_latencies(tmp_path, capsys):
    out_path = tmp_path / "card.json"
    rc = fabchaos.main(
        [
            "--seed", "5", "--scenario", "deliver_flap",
            "--quiet", "--out", str(out_path),
        ]
    )
    capsys.readouterr()
    assert rc == 0
    full = json.loads(out_path.read_text())
    assert "deterministic" in full and "observed" in full
    assert full["observed"]["stages"]["deliver_flap"]


def test_cli_rejects_unknown_scenario(capsys):
    assert fabchaos.main(["--scenario", "nope"]) == 2
    capsys.readouterr()


def test_cli_list_scenarios(capsys):
    assert fabchaos.main(["--list-scenarios"]) == 0
    out = capsys.readouterr().out
    for name in SMOKE:
        assert name in out


def test_scorecard_for_bench_compact_shape():
    card = fabchaos.scorecard_for_bench(seed=5, scale=0.4)
    assert card["ok"] is True
    assert set(card["scenarios"]) == set(SMOKE)
    assert len(card["det_sha"]) == 16


@pytest.mark.slow
def test_soak_runs_rounds():
    det, obs = SCENARIOS["soak"](1, StageClock(), 0.5, seconds=8.0)
    assert obs["rounds"] >= 1


def test_pipeline_dead_latches_across_stop():
    """A committer killed by a non-Exception escape stays dead even
    after a cleanup stop() — the soak triage workflow (drain -> stop ->
    inspect) must not be lied to."""
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.protos import protoutil
    from fabric_tpu.tools.fabchaos import _ChaosChannel

    ch = _ChaosChannel("latch")
    orig_store = ch.store_block

    def killer(block, prepared=None):
        raise KeyboardInterrupt("simulated interpreter-level escape")

    ch.store_block = killer
    pipe = CommitPipeline(ch)
    pipe.submit(protoutil.new_block(0, b""))
    pipe._committer.join(timeout=5)
    assert pipe.dead
    assert isinstance(pipe.last_error, KeyboardInterrupt)
    pipe.stop()
    assert pipe.dead  # latched: stop() does not mask the crash
    ch.store_block = orig_store
