"""CORE_*/ORDERER_* env overrides over node YAML config (reference viper
behavior, core/peer/config.go + orderer/common/localconfig)."""

from fabric_tpu.utils.config import apply_env_overrides


def _cfg():
    return {
        "peer": {
            "listenAddress": "127.0.0.1:7051",
            "localMspId": "Org1MSP",
            "gossip": {"bootstrap": "a:1"},
        },
        "ledger": {"deviceMVCC": False},
    }


def test_scalar_override_case_insensitive():
    cfg = apply_env_overrides(
        _cfg(), "CORE", {"CORE_PEER_LISTENADDRESS": "0.0.0.0:9999"}
    )
    assert cfg["peer"]["listenAddress"] == "0.0.0.0:9999"


def test_nested_and_typed_values():
    cfg = apply_env_overrides(
        _cfg(),
        "CORE",
        {
            "CORE_PEER_GOSSIP_BOOTSTRAP": "b:2",
            "CORE_LEDGER_DEVICEMVCC": "true",
        },
    )
    assert cfg["peer"]["gossip"]["bootstrap"] == "b:2"
    assert cfg["ledger"]["deviceMVCC"] is True  # YAML-typed


def test_unknown_paths_and_foreign_prefixes_ignored():
    cfg = apply_env_overrides(
        _cfg(),
        "CORE",
        {
            "CORE_PEER_NOSUCHKEY": "x",
            "CORE_NOPE_LISTENADDRESS": "y",
            "ORDERER_GENERAL_LISTENPORT": "7050",
            "PATH": "/usr/bin",
        },
    )
    assert cfg == _cfg()  # untouched


def test_section_cannot_be_replaced_by_scalar():
    cfg = apply_env_overrides(_cfg(), "CORE", {"CORE_PEER_GOSSIP": "zap"})
    assert cfg["peer"]["gossip"] == {"bootstrap": "a:1"}
