"""NWO-style integration: cryptogen + configtxgen CLIs generate the
artifacts, orderer + peer run as REAL subprocesses on localhost ports,
and the peer chaincode CLI drives an invoke/query round trip over gRPC
(reference integration/nwo + integration/e2e)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytest.importorskip(
    "cryptography", reason="CLI network bootstrap generates X.509 crypto-config"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(mod, *args, timeout=60):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", mod, *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )
    assert out.returncode == 0, f"{mod} {args} failed:\n{out.stdout}\n{out.stderr}"
    return out.stdout


def spawn(mod, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", mod, *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        cwd=REPO,
    )


def wait_listening(proc, needle, timeout=30):
    deadline = time.time() + timeout
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise AssertionError(
                    f"process exited {proc.returncode}: {''.join(lines)}"
                )
            continue
        lines.append(line)
        if needle in line:
            return line.rsplit(" ", 1)[-1].strip()
    raise AssertionError(f"never saw {needle!r}: {''.join(lines)}")


@pytest.fixture(scope="module")
def network(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("nwo")
    crypto = tmp / "crypto-config"

    # 1. cryptogen
    (tmp / "crypto-config.yaml").write_text(
        """
PeerOrgs:
  - Name: Org1
    Domain: org1.example.com
    MSPID: Org1MSP
    Template: {Count: 1}
    Users: {Count: 1}
OrdererOrgs:
  - Name: Orderer
    Domain: orderer.example.com
    MSPID: OrdererMSP
"""
    )
    run_cli(
        "fabric_tpu.cli.cryptogen",
        "generate",
        "--config",
        str(tmp / "crypto-config.yaml"),
        "--output",
        str(crypto),
    )
    org1 = crypto / "peerOrganizations" / "org1.example.com"
    oorg = crypto / "ordererOrganizations" / "orderer.example.com"
    assert (org1 / "msp" / "cacerts").is_dir()

    # 2. configtxgen: application-channel genesis block
    (tmp / "configtx.yaml").write_text(
        f"""
Profiles:
  OneOrgChannel:
    Orderer:
      OrdererType: solo
      BatchTimeout: 100ms
      BatchSize: {{MaxMessageCount: 10}}
      Organizations:
        - Name: OrdererMSP
          MSPID: OrdererMSP
          MSPDir: {oorg}/msp
    Application:
      Organizations:
        - Name: Org1MSP
          MSPID: Org1MSP
          MSPDir: {org1}/msp
"""
    )
    gblock = tmp / "mychannel.block"
    run_cli(
        "fabric_tpu.cli.configtxgen",
        "-profile",
        "OneOrgChannel",
        "-channelID",
        "mychannel",
        "-configPath",
        str(tmp / "configtx.yaml"),
        "-outputBlock",
        str(gblock),
    )
    assert gblock.stat().st_size > 0

    # 3. orderer + peer as real subprocesses (dynamic ports)
    (tmp / "orderer.yaml").write_text(
        f"""
General:
  ListenAddress: 127.0.0.1
  ListenPort: 0
  LocalMSPID: OrdererMSP
  LocalMSPDir: {oorg}/users/Admin@orderer.example.com/msp
  BootstrapFile: {gblock}
  WorkDir: {tmp}/orderer-data
"""
    )
    orderer_proc = spawn(
        "fabric_tpu.cli.orderer", "start", "--config", str(tmp / "orderer.yaml")
    )
    orderer_addr = wait_listening(orderer_proc, "orderer listening on")

    # user chaincode shipped as a python module (external-builder analog)
    (tmp / "kvcc_chaincode.py").write_text(
        '''
from fabric_tpu.chaincode import success, error_response

class KVChaincode:
    def init(self, stub):
        return success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return success(b"ok")
        if fn == "get":
            return success(stub.get_state(params[0]) or b"")
        return error_response("unknown " + fn)
'''
    )
    (tmp / "core.yaml").write_text(
        f"""
# these tests exercise CLI/node WIRING, not kernels (the device path is
# covered end-to-end by test_scale_e2e): the SW provider keeps commits
# instant instead of paying the fresh-process device-program load
BCCSP:
  Default: SW
peer:
  listenAddress: 127.0.0.1:0
  localMspId: Org1MSP
  mspConfigPath: {org1}/peers/peer0.org1.example.com/msp
  fileSystemPath: {tmp}/peer0-data
  orgMspDirs:
    Org1MSP: {org1}/msp
  ordererEndpoint: {orderer_addr}
  genesisBlocks: [{gblock}]
  chaincodes:
    kvcc: "OR('Org1MSP.member')"
  chaincodePath: [{tmp}]
  chaincodePlugins:
    kvcc: "kvcc_chaincode:KVChaincode"
"""
    )
    peer_proc = spawn(
        "fabric_tpu.cli.peer", "node", "start", "--config", str(tmp / "core.yaml")
    )
    peer_addr = wait_listening(peer_proc, "peer listening on")

    yield {
        "tmp": tmp,
        "orderer_addr": orderer_addr,
        "peer_addr": peer_addr,
        "user_msp": str(org1 / "users" / "User0@org1.example.com" / "msp"),
        "procs": (orderer_proc, peer_proc),
    }
    for proc in (orderer_proc, peer_proc):
        proc.send_signal(signal.SIGTERM)
    for proc in (orderer_proc, peer_proc):
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_configtxlator_roundtrip(network):
    tmp = network["tmp"]
    out = run_cli(
        "fabric_tpu.cli.configtxlator",
        "proto_decode",
        "--type",
        "common.Block",
        "--input",
        str(tmp / "mychannel.block"),
    )
    decoded = json.loads(out)
    # proto3 JSON omits zero-valued fields: genesis number 0 is absent
    assert decoded["header"].get("number", "0") in (0, "0")
    assert decoded["header"]["dataHash"]


def _query(network, chaincode, fn_args):
    import base64

    out = run_cli(
        "fabric_tpu.cli.peer",
        "chaincode",
        "query",
        "--peerAddresses",
        network["peer_addr"],
        "-C",
        "mychannel",
        "-n",
        chaincode,
        "-c",
        json.dumps({"Args": fn_args}),
        "--mspDir",
        network["user_msp"],
        "--mspID",
        "Org1MSP",
        "--b64",
    )
    return base64.b64decode(out.strip())


def test_cli_invoke_query_roundtrip(network):
    out = run_cli(
        "fabric_tpu.cli.peer",
        "chaincode",
        "invoke",
        "--peerAddresses",
        network["peer_addr"],
        "-o",
        network["orderer_addr"],
        "-C",
        "mychannel",
        "-n",
        "kvcc",
        "-c",
        json.dumps({"Args": ["put", "cli-key", "cli-value"]}),
        "--mspDir",
        network["user_msp"],
        "--mspID",
        "Org1MSP",
    )
    assert "submitted" in out
    # the peer's deliver loop commits within the batch timeout
    deadline = time.time() + 20
    value = b""
    while time.time() < deadline:
        value = _query(network, "kvcc", ["get", "cli-key"])
        if value == b"cli-value":
            break
        time.sleep(0.3)
    assert value == b"cli-value"


def test_cli_qscc_chain_info(network):
    from fabric_tpu.protos import common_pb2

    payload = _query(network, "qscc", ["GetChainInfo", "mychannel"])
    info = common_pb2.BlockchainInfo()
    info.ParseFromString(payload)
    assert info.height >= 1


def test_cli_channel_list(network):
    out = run_cli(
        "fabric_tpu.cli.peer",
        "channel",
        "list",
        "--peerAddress",
        network["peer_addr"],
        "--mspDir",
        network["user_msp"],
        "--mspID",
        "Org1MSP",
    )
    assert "mychannel" in out


def test_cli_lifecycle_package_install_query(network):
    tmp = network["tmp"]
    ccfile = tmp / "asset_cc.py"
    ccfile.write_text(
        "from fabric_tpu.chaincode.shim import success\n"
        "class Chaincode:\n"
        "    def init(self, stub):\n"
        "        return success()\n"
        "    def invoke(self, stub):\n"
        "        return success(b'hi')\n"
        "chaincode = Chaincode()\n"
    )
    pkg = tmp / "asset.tar.gz"
    out = run_cli(
        "fabric_tpu.cli.peer",
        "lifecycle",
        "chaincode",
        "package",
        str(pkg),
        "--path",
        str(ccfile),
        "--label",
        "asset_1",
    )
    assert pkg.stat().st_size > 0

    common = [
        "--peerAddress",
        network["peer_addr"],
        "--mspDir",
        network["user_msp"],
        "--mspID",
        "Org1MSP",
    ]
    out = run_cli(
        "fabric_tpu.cli.peer", "lifecycle", "chaincode", "install",
        str(pkg), *common,
    )
    assert "installed package: asset_1:" in out
    package_id = out.split("installed package: ")[1].strip()

    out = run_cli(
        "fabric_tpu.cli.peer", "lifecycle", "chaincode", "queryinstalled",
        *common,
    )
    assert package_id in out and "asset_1" in out

    out = run_cli(
        "fabric_tpu.cli.peer", "lifecycle", "chaincode", "approveformyorg",
        "-C", "mychannel", "-n", "asset", "--package-id", package_id,
        *common,
    )
    assert "approved" in out


def test_cli_discover_peers_and_endorsers(network):
    out = run_cli(
        "fabric_tpu.cli.discover",
        "peers",
        "--server",
        network["peer_addr"],
        "--channel",
        "mychannel",
        "--mspDir",
        network["user_msp"],
        "--mspID",
        "Org1MSP",
    )
    peers = json.loads(out)
    assert peers and peers[0]["endpoint"] == network["peer_addr"]
    assert "kvcc" in peers[0]["chaincodes"]

    out = run_cli(
        "fabric_tpu.cli.discover",
        "endorsers",
        "--server",
        network["peer_addr"],
        "--channel",
        "mychannel",
        "--chaincode",
        "kvcc",
        "--mspDir",
        network["user_msp"],
        "--mspID",
        "Org1MSP",
    )
    desc = json.loads(out)
    assert desc["chaincode"] == "kvcc" and desc["layouts"]


def test_cli_idemixgen_roundtrip(tmp_path):
    out_dir = tmp_path / "idemix"
    run_cli(
        "fabric_tpu.cli.idemixgen", "ca-keygen", "--output", str(out_dir)
    )
    assert (out_dir / "ca" / "IssuerSecretKey").exists()
    assert (out_dir / "msp" / "IssuerPublicKey").exists()
    assert (out_dir / "msp" / "RevocationPublicKey").exists()
    run_cli(
        "fabric_tpu.cli.idemixgen",
        "signerconfig",
        "--output",
        str(out_dir),
        "-u",
        "org9",
        "-e",
        "alice",
    )
    signer_path = out_dir / "user" / "SignerConfig"
    assert signer_path.exists()

    # generated material is loadable and usable end-to-end
    from fabric_tpu.msp.idemix_msp import IdemixMSP, IdemixSigningIdentity
    from fabric_tpu.protos import msp_config_pb2

    cfg = msp_config_pb2.IdemixMSPConfig()
    cfg.name = "IdemixOrg"
    cfg.ipk = (out_dir / "msp" / "IssuerPublicKey").read_bytes()
    cfg.revocation_pk = (out_dir / "msp" / "RevocationPublicKey").read_bytes()
    signer_cfg = msp_config_pb2.IdemixMSPSignerConfig()
    signer_cfg.ParseFromString(signer_path.read_bytes())
    cfg.signer.CopyFrom(signer_cfg)
    msp = IdemixMSP(cfg)
    ident = IdemixSigningIdentity(msp, signer_cfg)
    sig = ident.sign(b"hello idemix")
    parsed = msp.deserialize_identity(ident.serialize())
    msp.validate(parsed)
    msp.verify(parsed, b"hello idemix", sig)


def test_cli_channel_fetch_selectors(network, tmp_path):
    """peer channel fetch oldest|newest|config|<n>, from the orderer
    and from the peer's own deliver service (fetch.go selectors)."""
    from fabric_tpu.protos import common_pb2

    def fetch(selector, out_name, source_args):
        out_path = str(tmp_path / out_name)
        run_cli(
            "fabric_tpu.cli.peer",
            "channel",
            "fetch",
            selector,
            out_path,
            "-c",
            "mychannel",
            *source_args,
            "--mspDir",
            network["user_msp"],
            "--mspID",
            "Org1MSP",
        )
        block = common_pb2.Block()
        with open(out_path, "rb") as f:
            block.ParseFromString(f.read())
        return block

    orderer = ["-o", network["orderer_addr"]]
    peer = ["--peerAddress", network["peer_addr"]]

    genesis = fetch("oldest", "g.block", orderer)
    assert genesis.header.number == 0
    newest = fetch("newest", "n.block", orderer)
    assert newest.header.number >= genesis.header.number
    config = fetch("config", "c.block", orderer)
    assert config.header.number == 0  # only config block is the genesis
    by_number = fetch("0", "z.block", peer)  # peer-side fetch
    assert by_number.header.number == 0
    peer_newest = fetch("newest", "pn.block", peer)
    assert peer_newest.header.number >= 0


def test_cli_snapshot_lifecycle(network):
    """peer snapshot submitrequest/listpending/cancelrequest against the
    live peer's /protos.Snapshot service (snapshot_service.go:25-87),
    then an invoke commits the requested height and the snapshot
    directory appears under the peer's workdir."""
    # snapshot admin ops require the channel Admins policy; sign as the
    # org admin, not User0
    admin_msp = network["user_msp"].replace(
        "User0@org1.example.com", "Admin@org1.example.com"
    )
    common = [
        "-C",
        "mychannel",
        "--peerAddress",
        network["peer_addr"],
        "--mspDir",
        admin_msp,
        "--mspID",
        "Org1MSP",
    ]

    # a far-future request: submitted, listed, cancelled
    run_cli(
        "fabric_tpu.cli.peer", "snapshot", "submitrequest", "-b", "999", *common
    )
    out = run_cli("fabric_tpu.cli.peer", "snapshot", "listpending", *common)
    assert "[999]" in out
    run_cli(
        "fabric_tpu.cli.peer", "snapshot", "cancelrequest", "-b", "999", *common
    )
    out = run_cli("fabric_tpu.cli.peer", "snapshot", "listpending", *common)
    assert "[]" in out

    # height-0 request = next committed block; the invoke commits it
    run_cli(
        "fabric_tpu.cli.peer", "snapshot", "submitrequest", "-b", "0", *common
    )
    run_cli(
        "fabric_tpu.cli.peer",
        "chaincode",
        "invoke",
        "--peerAddresses",
        network["peer_addr"],
        "-o",
        network["orderer_addr"],
        "-C",
        "mychannel",
        "-n",
        "kvcc",
        "-c",
        json.dumps({"Args": ["put", "snap-key", "snap-value"]}),
        "--mspDir",
        network["user_msp"],
        "--mspID",
        "Org1MSP",
    )
    snap_root = network["tmp"] / "peer0-data" / "snapshots" / "mychannel"
    deadline = time.time() + 20
    while time.time() < deadline:
        pending = run_cli(
            "fabric_tpu.cli.peer", "snapshot", "listpending", *common
        )
        if "[]" in pending and snap_root.exists() and any(snap_root.iterdir()):
            break
        time.sleep(0.3)
    assert snap_root.exists() and any(snap_root.iterdir())
    from fabric_tpu.ledger.snapshot import verify_snapshot

    snap_dir = sorted(snap_root.iterdir())[0]
    meta = verify_snapshot(str(snap_dir))
    assert meta["channel_name"] == "mychannel"
