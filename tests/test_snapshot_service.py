"""Snapshot request manager + gRPC service (reference
core/ledger/kvledger/snapshot_mgr.go and
core/ledger/snapshotgrpc/snapshot_service.go:25-87)."""

import os

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from fabric_tpu.comm.server import GRPCServer, channel_to
from fabric_tpu.comm.services import register_snapshot_service
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.ledger.snapshot import (
    SnapshotRequestManager,
    verify_snapshot,
)
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.peer import Channel
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil
from fabric_tpu.validation.validator import (
    ChaincodeDefinition,
    ChaincodeRegistry,
)

PROVIDER = SoftwareProvider()
CHANNEL = "snapsvc"


@pytest.fixture()
def world(tmp_path):
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )

    org = generate_org("org1.snapsvc", "Org1MSP")
    mgr = MSPManager([org.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("mycc", from_dsl("OR('Org1MSP.member')"))]
    )
    channel = Channel(CHANNEL, str(tmp_path / "ledger"), mgr, registry, PROVIDER)
    client = SigningIdentity(org.users[0], PROVIDER)
    endorser = SigningIdentity(org.peers[0], PROVIDER)

    prev = b"\x11" * 32

    def commit(i):
        nonlocal prev
        results = serialize_tx_rwset(
            rw.TxRwSet(
                (
                    rw.NsRwSet(
                        "mycc", (), (rw.KVWrite(f"k{i}", False, b"v"),)
                    ),
                )
            )
        )
        bundle = create_proposal(client, CHANNEL, "mycc", [b"put", b"%d" % i])
        resp = endorse_proposal(bundle, endorser, results)
        env = create_signed_tx(bundle, client, [resp])
        block = protoutil.new_block(channel.ledger.height, prev)
        block.data.data.append(env.SerializeToString())
        protoutil.seal_block(block)
        prev = protoutil.block_header_hash(block.header)
        channel.store_block(block)

    return {"channel": channel, "commit": commit, "tmp": tmp_path, "org": org}


def test_manager_lifecycle_and_generation(world):
    ch = world["channel"]
    world["commit"](0)  # height 1
    mgr = SnapshotRequestManager(ch.ledger, str(world["tmp"] / "snaps"))

    # height 0 = next committed block (current height)
    h = mgr.submit(0)
    assert h == ch.ledger.height
    with pytest.raises(ValueError):
        mgr.submit(h)  # duplicate
    mgr.submit(h + 2)
    assert mgr.pending() == [h, h + 2]
    mgr.cancel(h + 2)
    assert mgr.pending() == [h]
    with pytest.raises(ValueError):
        mgr.cancel(99)

    world["commit"](1)  # commits block number h
    mgr.on_block_committed(wait=True)
    assert mgr.pending() == []
    out_dir = mgr.generated[h]
    meta = verify_snapshot(out_dir)
    assert meta["channel_name"] == CHANNEL
    assert meta["last_block_number"] == h
    assert os.path.basename(out_dir) == str(h)
    with pytest.raises(ValueError):
        mgr.submit(h)  # below the current height now


def test_grpc_service_roundtrip(world):
    ch = world["channel"]
    world["commit"](0)
    mgr = SnapshotRequestManager(ch.ledger, str(world["tmp"] / "snaps"))
    server = GRPCServer("127.0.0.1:0")
    register_snapshot_service(server, lambda cid: mgr if cid == CHANNEL else None)
    addr = server.start()

    signer = SigningIdentity(world["org"].users[0], PROVIDER)

    def signed_req(msg):
        raw = msg.SerializeToString()
        return peer_pb2.SignedSnapshotRequest(
            request=raw, signature=signer.sign(raw)
        )

    def shdr():
        h = common_pb2.SignatureHeader()
        h.creator = signer.serialize()
        return h.SerializeToString()

    conn = channel_to(addr)
    try:
        from google.protobuf import empty_pb2

        gen = conn.unary_unary(
            "/protos.Snapshot/Generate",
            request_serializer=peer_pb2.SignedSnapshotRequest.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString,
        )
        pend = conn.unary_unary(
            "/protos.Snapshot/QueryPendings",
            request_serializer=peer_pb2.SignedSnapshotRequest.SerializeToString,
            response_deserializer=peer_pb2.QueryPendingSnapshotsResponse.FromString,
        )
        cancel = conn.unary_unary(
            "/protos.Snapshot/Cancel",
            request_serializer=peer_pb2.SignedSnapshotRequest.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString,
        )
        gen(
            signed_req(
                peer_pb2.SnapshotRequest(
                    signature_header=shdr(), channel_id=CHANNEL, block_number=5
                )
            )
        )
        out = pend(
            signed_req(
                peer_pb2.SnapshotQuery(
                    signature_header=shdr(), channel_id=CHANNEL
                )
            )
        )
        assert list(out.block_numbers) == [5]
        cancel(
            signed_req(
                peer_pb2.SnapshotRequest(
                    signature_header=shdr(), channel_id=CHANNEL, block_number=5
                )
            )
        )
        out = pend(
            signed_req(
                peer_pb2.SnapshotQuery(
                    signature_header=shdr(), channel_id=CHANNEL
                )
            )
        )
        assert list(out.block_numbers) == []
    finally:
        conn.close()
        server.stop()


def test_join_channel_by_snapshot_via_cscc(world, tmp_path):
    """cscc JoinChainBySnapshot end-to-end at the node layer (reference
    configure.go joinChainBySnapshot -> core/peer
    CreateChannelFromSnapshot): export a snapshot from one channel,
    join a FRESH PeerNode from it, and see the state + dedup carried
    over with the ledger resuming at the snapshot height."""
    from fabric_tpu.ledger.snapshot import generate_snapshot
    from fabric_tpu.msp.identity import MSPManager
    from fabric_tpu.nodes.peer import PeerNode
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.validation.validator import (
        ChaincodeDefinition,
        ChaincodeRegistry,
    )

    ch = world["channel"]
    world["commit"](0)
    world["commit"](1)
    snap_dir = str(tmp_path / "export")
    meta = generate_snapshot(ch.ledger, snap_dir)
    assert meta["channel_name"] == CHANNEL

    org = world["org"]
    node = PeerNode(
        str(tmp_path / "fresh-peer"),
        MSPManager([org.msp(provider=PROVIDER)]),
        SigningIdentity(org.peers[0], PROVIDER),
        lambda cid: ChaincodeRegistry(
            [ChaincodeDefinition("mycc", from_dsl("OR('Org1MSP.member')"))]
        ),
        provider=PROVIDER,
    )
    try:
        joined = node.join_channel_by_snapshot(snap_dir)
        assert joined == CHANNEL
        fresh = node.channels[CHANNEL]
        assert fresh.ledger.height == ch.ledger.height
        vv = fresh.ledger.state_db.get_state("mycc", "k0")
        assert vv is not None and vv.value == b"v"
        # duplicate-txid dedup carried over from the snapshot txid list
        assert CHANNEL in node.snapshot_managers
        with pytest.raises(ValueError):
            node.join_channel_by_snapshot(snap_dir)  # already joined
    finally:
        node.stop()
