"""fabreg unit tests: a firing fixture + negative control per rule,
suppression semantics, CLI plumbing, the toolkit chassis, and the repo
self-check (the CI gate invariant: ``fabreg fabric_tpu/ tests/
bench.py`` reports 0 unsuppressed findings).

Fixture code lives in *strings* on purpose: the repo self-check scans
this file too, and only genuine AST calls / genuine comments may feed
the rules (a ``disable=`` inside a string is data — asserted below).
"""

import json
import textwrap
from pathlib import Path

import pytest

from fabric_tpu.tools import fabreg, toolkit

REPO_ROOT = Path(__file__).resolve().parent.parent


def rule_ids(findings):
    return [f.rule for f in findings]


def analyze(sources, rules=None, readme=None):
    findings, _stats = fabreg.analyze_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()},
        rules,
        readme_text=readme,
    )
    return findings


# a minimal env registry fixture (AST-parsed, never imported)
ENVREG = """
    ENV_VARS = (
        EnvVar("FABRIC_TPU_DECLARED", "int", "1", "m.py", "a knob"),
    )
"""
ENVREG_PATH = "fabric_tpu/common/envreg.py"

# a minimal canonical metric table fixture
FABOBS = """
    CANONICAL_METRICS = (
        MetricSpec("fabric_x_total", "counter", ("mode",), "h", "seam"),
        MetricSpec("fabric_y_seconds", "histogram", (), "h", "seam"),
    )
"""
FABOBS_PATH = "fabric_tpu/common/fabobs.py"

EMITTERS = textwrap.dedent(
    """
    def hook():
        obs_count("fabric_x_total", 2, mode="a")
        obs_observe("fabric_y_seconds", 0.1)
    """
)


# ---------------------------------------------------------------------------
# env-undeclared / env-dead
# ---------------------------------------------------------------------------


def test_env_undeclared_fires_on_unregistered_read():
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": """
                import os
                V = os.environ.get("FABRIC_TPU_MYSTERY", "")
            """,
        },
        rules=["env-undeclared"],
    )
    assert rule_ids(findings) == ["env-undeclared"]
    assert "FABRIC_TPU_MYSTERY" in findings[0].message


def test_env_undeclared_covers_getenv_subscript_and_setdefault():
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": """
                import os
                A = os.getenv("FABRIC_TPU_A")
                B = os.environ["FABRIC_TPU_B"]
                os.environ.setdefault("FABRIC_TPU_C", "1")
            """,
        },
        rules=["env-undeclared"],
    )
    assert rule_ids(findings) == ["env-undeclared"] * 3


def test_env_undeclared_sees_reads_through_helper_wrappers():
    # idemix/batch.py's `_env_int("FABRIC_TPU_X", 8)` pattern: a full
    # env name as a call's first argument is a read, wrapper or not —
    # a helper must not launder a read past the registry
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": """
                def f():
                    return _env_int("FABRIC_TPU_WRAPPED", 8)
            """,
        },
        rules=["env-undeclared"],
    )
    assert rule_ids(findings) == ["env-undeclared"]
    # ...while monkeypatch-style setters stay references, not reads
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": """
                def f(monkeypatch):
                    monkeypatch.setenv("FABRIC_TPU_SET_ONLY", "1")
            """,
        },
        rules=["env-undeclared"],
    )
    assert findings == []


def test_env_undeclared_negative_declared_read_is_clean():
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": """
                import os
                V = os.environ.get("FABRIC_TPU_DECLARED", "1")
            """,
        },
        rules=["env-undeclared"],
    )
    assert findings == []


def test_env_undeclared_fires_without_a_registry_at_all():
    findings = analyze(
        {
            "fabric_tpu/m.py": """
                import os
                V = os.environ.get("FABRIC_TPU_MYSTERY", "")
            """
        },
        rules=["env-undeclared"],
    )
    assert rule_ids(findings) == ["env-undeclared"]
    assert "no env registry" in findings[0].message


def test_env_dead_fires_on_readerless_row():
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": "X = 1\n",
        },
        rules=["env-dead"],
    )
    assert rule_ids(findings) == ["env-dead"]
    assert findings[0].path == ENVREG_PATH
    assert "FABRIC_TPU_DECLARED" in findings[0].message


def test_env_dead_negative_any_reference_keeps_a_row_alive():
    # an accessor read...
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": """
                import os
                V = os.environ.get("FABRIC_TPU_DECLARED", "1")
            """,
        },
        rules=["env-dead"],
    )
    assert findings == []
    # ...or a bare string mention (a test exercising the knob)
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": 'NAME = "FABRIC_TPU_DECLARED"\n',
        },
        rules=["env-dead"],
    )
    assert findings == []


def test_env_dead_registry_self_reference_does_not_count():
    # the row's own name literal inside envreg.py must not make it live
    findings = analyze({ENVREG_PATH: ENVREG}, rules=["env-dead"])
    assert rule_ids(findings) == ["env-dead"]


# ---------------------------------------------------------------------------
# metric-unknown / metric-label-drift / metric-orphan
# ---------------------------------------------------------------------------


def test_metric_unknown_fires_on_unregistered_family():
    findings = analyze(
        {
            FABOBS_PATH: FABOBS,
            "fabric_tpu/serve/m.py": EMITTERS + textwrap.dedent(
                """
                def bad():
                    obs_count("fabric_zzz_total")
                """
            ),
        },
        rules=["metric-unknown"],
    )
    assert rule_ids(findings) == ["metric-unknown"]
    assert "fabric_zzz_total" in findings[0].message


def test_metric_unknown_negative_canonical_emit_is_clean():
    findings = analyze(
        {FABOBS_PATH: FABOBS, "fabric_tpu/serve/m.py": EMITTERS},
        rules=["metric-unknown"],
    )
    assert findings == []


def test_metric_label_drift_fires_on_missing_and_extra_labels():
    findings = analyze(
        {
            FABOBS_PATH: FABOBS,
            "fabric_tpu/serve/m.py": """
                def bad():
                    obs_count("fabric_x_total")
                    obs_observe("fabric_y_seconds", 0.1, stage="x")
            """,
        },
        rules=["metric-label-drift"],
    )
    assert rule_ids(findings) == ["metric-label-drift"] * 2


def test_metric_label_drift_fires_on_kind_mismatch():
    findings = analyze(
        {
            FABOBS_PATH: FABOBS,
            "fabric_tpu/serve/m.py": """
                def bad():
                    obs_gauge("fabric_x_total", 1.0, mode="a")
            """,
        },
        rules=["metric-label-drift"],
    )
    assert rule_ids(findings) == ["metric-label-drift"]
    assert "counter" in findings[0].message


def test_metric_label_drift_negative_exact_labels_clean():
    findings = analyze(
        {FABOBS_PATH: FABOBS, "fabric_tpu/serve/m.py": EMITTERS},
        rules=["metric-label-drift"],
    )
    assert findings == []


def test_metric_orphan_fires_without_an_emitter():
    findings = analyze({FABOBS_PATH: FABOBS}, rules=["metric-orphan"])
    assert rule_ids(findings) == ["metric-orphan"] * 2
    assert all(f.path == FABOBS_PATH for f in findings)


def test_metric_orphan_negative_emitted_families_clean():
    findings = analyze(
        {FABOBS_PATH: FABOBS, "fabric_tpu/serve/m.py": EMITTERS},
        rules=["metric-orphan"],
    )
    assert findings == []


def test_metric_rules_ignore_code_outside_the_package():
    # tests deliberately emit unknown families (exercising the runtime
    # swallow); only fabric_tpu/ files are held to the table
    findings = analyze(
        {
            FABOBS_PATH: FABOBS,
            "fabric_tpu/serve/m.py": EMITTERS,
            "tests/test_x.py": """
                def probe():
                    obs_count("fabric_not_canonical_total")
            """,
        },
        rules=["metric-unknown"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# fault-site-drift
# ---------------------------------------------------------------------------

CHAOS_WITH_SITE = """
    PLAN = "x.seam=raise:0.5"
"""
CHAOS_PATH = "fabric_tpu/tools/fabchaos.py"

FAULT_MODULE = """
    def f():
        fault_point("x.seam")
"""


def test_fault_site_drift_fires_when_missing_from_readme():
    findings = analyze(
        {"fabric_tpu/m.py": FAULT_MODULE, CHAOS_PATH: CHAOS_WITH_SITE},
        rules=["fault-site-drift"],
        readme="no sites here",
    )
    assert rule_ids(findings) == ["fault-site-drift"]
    assert "README" in findings[0].message


def test_fault_site_drift_fires_when_no_scenario_exercises_it():
    findings = analyze(
        {"fabric_tpu/m.py": FAULT_MODULE, CHAOS_PATH: "PLAN = 'other'\n"},
        rules=["fault-site-drift"],
        readme="| `x.seam` |",
    )
    assert rule_ids(findings) == ["fault-site-drift"]
    assert "not exercised" in findings[0].message


def test_fault_site_drift_negative_documented_and_exercised():
    findings = analyze(
        {"fabric_tpu/m.py": FAULT_MODULE, CHAOS_PATH: CHAOS_WITH_SITE},
        rules=["fault-site-drift"],
        readme="| `x.seam` |",
    )
    assert findings == []


def test_fault_site_drift_without_readme_checks_scenarios_only():
    # no README text available -> only the fabchaos-coverage half runs
    findings = analyze(
        {"fabric_tpu/m.py": FAULT_MODULE, CHAOS_PATH: CHAOS_WITH_SITE},
        rules=["fault-site-drift"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# suppression-stale
# ---------------------------------------------------------------------------


def test_suppression_stale_fires_on_dead_fablint_comment():
    findings = analyze(
        {
            "fabric_tpu/m.py": (
                "X = 1  # fablint: disable=broad-except  # nothing here\n"
            )
        },
        rules=["suppression-stale"],
    )
    assert rule_ids(findings) == ["suppression-stale"]
    assert "disable=broad-except" in findings[0].message


def test_suppression_stale_negative_live_fablint_comment():
    findings = analyze(
        {
            "fabric_tpu/m.py": (
                "def f(x=[]):  # fablint: disable=mutable-default  # ok\n"
                "    return x\n"
            )
        },
        rules=["suppression-stale"],
    )
    assert findings == []


def test_suppression_stale_own_fabreg_comments():
    # dead: nothing to suppress on that line
    findings = analyze(
        {
            ENVREG_PATH: ENVREG,
            "fabric_tpu/m.py": (
                "X = 1  # fabreg: disable=env-undeclared  # nothing\n"
            ),
        },
        rules=["suppression-stale"],
    )
    assert rule_ids(findings) == ["suppression-stale"]
    # live: the comment really suppresses an env-undeclared finding
    live_sources = {
        ENVREG_PATH: ENVREG,
        "fabric_tpu/m.py": (
            "import os\n"
            'V = os.environ.get("FABRIC_TPU_GHOST", "")'
            "  # fabreg: disable=env-undeclared  # migration grace\n"
        ),
    }
    findings = analyze(
        live_sources, rules=["env-undeclared", "suppression-stale"]
    )
    assert findings == []
    # ...and staleness judges the FULL rule set even when the caller
    # runs suppression-stale alone: the live comment stays unreported
    findings = analyze(live_sources, rules=["suppression-stale"])
    assert findings == []


def test_suppression_stale_covers_fabreg_comments_outside_the_package():
    # sibling-tool comments outside fabric_tpu/ are inert (their gates
    # never look there) — but fabreg's own gate scans tests/, so its
    # comments are judged wherever they are honored
    findings = analyze(
        {
            "tests/test_x.py": (
                "X = 1  # fabreg: disable=env-undeclared  # nothing\n"
                "Y = 2  # fablint: disable=broad-except  # inert there\n"
            )
        },
        rules=["suppression-stale"],
    )
    assert rule_ids(findings) == ["suppression-stale"]
    assert "fabreg" in findings[0].message


def test_suppression_inside_a_string_is_data_not_a_comment():
    findings = analyze(
        {
            "fabric_tpu/m.py": (
                'S = "x = 1  # fablint: disable=broad-except"\n'
            )
        },
        rules=["suppression-stale"],
    )
    assert findings == []


def test_suppression_stale_fabdep_leg_runs_on_disk(tmp_path):
    pkg = tmp_path / "fabric_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    # dead comment: no shared write anywhere near it
    (pkg / "mod.py").write_text(
        "X = 1  # fabdep: disable=unguarded-shared-write  # nothing\n"
    )
    findings, _stats = fabreg.analyze_paths(
        [str(pkg)], rule_ids=["suppression-stale"]
    )
    assert rule_ids(findings) == ["suppression-stale"]
    # live comment: the write really races and the comment absorbs it
    (pkg / "mod.py").write_text(
        textwrap.dedent(
            """
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._run, daemon=True)
                    self._t.start()

                def _run(self):
                    while True:
                        self.count += 1  # fabdep: disable=unguarded-shared-write  # fixture

                def poke(self):
                    self.count = 0  # fabdep: disable=unguarded-shared-write  # fixture
            """
        )
    )
    findings, _stats = fabreg.analyze_paths(
        [str(pkg)], rule_ids=["suppression-stale"]
    )
    assert findings == []


# ---------------------------------------------------------------------------
# suppression application + CLI
# ---------------------------------------------------------------------------


def test_findings_respect_fabreg_suppressions():
    findings, suppressed = fabreg.analyze_source(
        "import os\n"
        'V = os.environ.get("FABRIC_TPU_GHOST", "")'
        "  # fabreg: disable=env-undeclared  # grace\n",
        "fabric_tpu/m.py",
        rule_ids=["env-undeclared"],
    )
    assert findings == []
    assert suppressed == 1


def test_cli_list_rules_and_json(tmp_path, capsys):
    assert fabreg.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in fabreg.RULES:
        assert rid in out

    target = tmp_path / "m.py"
    target.write_text('import os\nV = os.environ.get("FABRIC_TPU_X", "")\n')
    rc = fabreg.main(["--json", "--rules", "env-undeclared", str(target)])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["files"] == 1
    assert [f["rule"] for f in payload["findings"]] == ["env-undeclared"]


def test_cli_usage_errors(tmp_path):
    assert fabreg.main([]) == 2
    assert fabreg.main([str(tmp_path / "missing.py")]) == 2
    assert fabreg.main(["--rules", "no-such", str(tmp_path)]) == 2
    assert (
        fabreg.main(["--readme", str(tmp_path / "no.md"), str(tmp_path)])
        == 2
    )


def test_unknown_rule_id_raises_in_api():
    with pytest.raises(ValueError):
        fabreg.analyze_sources({"m.py": "X = 1\n"}, rule_ids=["bogus"])


# ---------------------------------------------------------------------------
# the toolkit chassis (the port contract: one Finding, one walker, one
# suppression grammar across all four analyzers)
# ---------------------------------------------------------------------------


def test_all_four_tools_share_the_toolkit_chassis():
    from fabric_tpu.tools import fabdep, fabflow, fablint

    for tool in (fablint, fabdep, fabflow, fabreg):
        assert tool.Finding is toolkit.Finding
        assert tool.DEFAULT_EXCLUDES == toolkit.DEFAULT_EXCLUDES
    assert fablint.iter_py_files is toolkit.iter_py_files
    assert fabflow.iter_py_files is toolkit.iter_py_files


def test_toolkit_suppression_grammar_reasons_and_all():
    sup = toolkit.parse_suppressions(
        "x = 1  # fabreg: disable=env-dead,metric-orphan  # the why\n",
        "fabreg",
    )
    assert sup == {1: ({"env-dead", "metric-orphan"}, "the why")}
    kept, suppressed = toolkit.apply_suppressions(
        [toolkit.Finding("anything", "m.py", 2, 0, "m")],
        {2: {"all"}},
    )
    assert kept == [] and len(suppressed) == 1


# ---------------------------------------------------------------------------
# the repo self-check: the gate invariant
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def repo_findings():
    return fabreg.analyze_paths(
        [
            str(REPO_ROOT / "fabric_tpu"),
            str(REPO_ROOT / "tests"),
            str(REPO_ROOT / "bench.py"),
        ],
        readme=str(REPO_ROOT / "README.md"),
    )


def test_repo_self_check_is_clean(repo_findings):
    findings, stats = repo_findings
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message}" for f in findings
    )
    assert stats["files"] > 200  # the walk actually covered the tree


def test_repo_env_registry_matches_the_tree(repo_findings):
    # every var the registry declares is used, and (via the clean
    # self-check above) every read is declared — the two directions of
    # the env contract.  Spot-pin the PR motivator: the cache-debug
    # forensics knob conftest reads is declared.
    from fabric_tpu.common import envreg

    assert "FABRIC_TPU_CACHE_DEBUG" in envreg.ENV_BY_NAME
    assert len(envreg.ENV_VARS) >= 24
    assert len({v.name for v in envreg.ENV_VARS}) == len(envreg.ENV_VARS)
    for var in envreg.ENV_VARS:
        assert var.name.startswith("FABRIC_TPU_")
        assert var.type and var.default and var.consumer and var.doc
