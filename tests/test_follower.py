"""Orderer follower/onboarding (reference orderer/common/follower
follower_chain.go + onboarding): a non-consenter orderer replicates a
channel from the cluster, serves deliver while doing so, and promotes
itself to a raft member when the channel config adds it."""

import socket
import time

import pytest

pytest.importorskip(
    "cryptography", reason="follower chains join via cryptogen-built orgs"
)

from fabric_tpu.channelconfig import (
    ApplicationProfile,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    genesis_block,
)
from fabric_tpu.comm.server import channel_to
from fabric_tpu.comm.services import broadcast_envelope, deliver_stream
from fabric_tpu.deliver.client import seek_envelope
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.nodes.orderer import OrdererNode
from fabric_tpu.orderer.follower import FollowerChain, is_member
from fabric_tpu.channelconfig.bundle import bundle_from_genesis_block
from fabric_tpu.protos import ab_pb2, common_pb2, protoutil

CHANNEL = "followchan"


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _profile(org1, oorg, consenter_ports):
    return Profile(
        application=ApplicationProfile(
            organizations=[OrganizationProfile("Org1MSP", org1.msp_config())]
        ),
        orderer=OrdererProfile(
            orderer_type="etcdraft",
            batch_timeout="100ms",
            max_message_count=1,
            organizations=[
                OrganizationProfile("OrdererMSP", oorg.msp_config())
            ],
            raft_consenters=[
                ("127.0.0.1", p, b"", b"") for p in consenter_ports
            ],
        ),
    )


def _renumber_config_block(config_block, number, prev_hash):
    """Re-chain a config block's envelope at a later height (stand-in for
    a committed config UPDATE block in unit tests)."""
    block = protoutil.new_block(number, prev_hash)
    for d in config_block.data.data:
        block.data.data.append(d)
    protoutil.seal_block(block)
    return block


def test_follower_unit_promotion(tmp_path):
    """Fake deliver endpoints: the follower replicates, rejects nothing,
    and promotes itself when a config block adds it to the consenter
    set."""
    org1 = generate_org("org1.follow", "Org1MSP")
    oorg = generate_org("orderer.follow", "OrdererMSP")
    p1, p2 = _free_ports(2)
    gblock = genesis_block(_profile(org1, oorg, [p1]), CHANNEL)
    grown = genesis_block(_profile(org1, oorg, [p1, p2]), CHANNEL)
    block1 = _renumber_config_block(
        grown, 1, protoutil.block_header_hash(gblock.header)
    )
    chain_blocks = [gblock, block1]

    def endpoint_factory(addrs):
        def endpoint(env):
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            seek = ab_pb2.SeekInfo()
            seek.ParseFromString(payload.data)
            for b in chain_blocks[seek.start.specified.number :]:
                resp = ab_pb2.DeliverResponse()
                resp.block.CopyFrom(b)
                yield resp

        return [endpoint]

    bundle = bundle_from_genesis_block(gblock)
    assert not is_member(bundle, 2)
    promoted = []
    follower = FollowerChain(
        CHANNEL,
        gblock,
        bundle,
        node_id=2,
        wal_dir=str(tmp_path / "etcdraft"),
        endpoint_factory=endpoint_factory,
        on_become_member=promoted.append,
    )
    # a genesis join block seeds the ledger immediately: height 1 > join
    # number 0, so the follower reports active (not onboarding)
    assert follower.status == "active"
    assert follower.height == 1
    follower.start()
    assert _wait(lambda: bool(promoted), timeout=10.0)
    assert promoted[0].height == 2
    assert is_member(promoted[0].bundle, 2)
    follower.stop()


def test_follower_replicates_and_serves_deliver(tmp_path):
    """Socket-level: a 2-consenter cluster orders txs; a third orderer
    joins as a non-member follower, replicates over real deliver
    streams, reports participation status, and serves deliver itself."""
    org1 = generate_org("org1.follow2", "Org1MSP")
    oorg = generate_org("orderer.follow2", "OrdererMSP")
    ports = _free_ports(3)
    gblock = genesis_block(_profile(org1, oorg, ports[:2]), CHANNEL)

    nodes = []
    try:
        for i, port in enumerate(ports[:2]):
            node = OrdererNode(
                str(tmp_path / f"orderer{i}"),
                signer=SigningIdentity(oorg.peers[0]),
                listen_address=f"127.0.0.1:{port}",
                raft_node_id=i + 1,
                raft_tick_seconds=0.05,
            )
            node.join_channel(gblock)
            node.start()
            nodes.append(node)

        def leaders():
            return [
                n
                for n in nodes
                if n.registrar.get_chain(CHANNEL) is not None
                and n.registrar.get_chain(CHANNEL).chain.node.role == "leader"
            ]

        assert _wait(lambda: len(leaders()) == 1)

        follower_node = OrdererNode(
            str(tmp_path / "orderer-follower"),
            signer=SigningIdentity(oorg.peers[0]),
            listen_address=f"127.0.0.1:{ports[2]}",
            raft_node_id=3,
            raft_tick_seconds=0.05,
        )
        chain = follower_node.join_channel(gblock)
        assert isinstance(chain, FollowerChain)
        follower_node.start()
        nodes.append(follower_node)

        # order a tx through the leader; the follower replicates it
        client = SigningIdentity(org1.users[0])
        payload = common_pb2.Payload()
        chdr = protoutil.make_channel_header(
            common_pb2.ENDORSER_TRANSACTION, CHANNEL
        )
        payload.header.channel_header = chdr.SerializeToString()
        shdr = protoutil.make_signature_header(
            client.serialize(), client.new_nonce()
        )
        payload.header.signature_header = shdr.SerializeToString()
        payload.data = b"tx-1"
        env = common_pb2.Envelope()
        env.payload = payload.SerializeToString()
        env.signature = client.sign(env.payload)
        ch = channel_to(leaders()[0].addr)
        resp = broadcast_envelope(ch, env)
        ch.close()
        assert resp.status == common_pb2.SUCCESS

        assert _wait(lambda: chain.height >= 2), chain.height
        info = follower_node.registrar.channel_info(CHANNEL)
        assert info == {
            "name": CHANNEL,
            "height": chain.height,
            "status": "active",
            "consensusRelation": "follower",
        }
        assert CHANNEL in follower_node.registrar.channel_list()

        # the follower serves deliver for its replicated range
        conn = channel_to(follower_node.addr)
        got = []
        for resp in deliver_stream(
            conn, seek_envelope(CHANNEL, 0, stop=1)
        ):
            if resp.WhichOneof("Type") == "block":
                got.append(resp.block.header.number)
            else:
                break
        conn.close()
        assert got == [0, 1]
    finally:
        for node in nodes:
            try:
                node.stop()
            except Exception:
                pass


def test_consenter_set_config_update_bridges_to_raft(tmp_path):
    """A committed config block that grows the etcdraft consenter set
    becomes a raft membership change on the chain (etcdraft
    detectConfChange analog): peers expand so an onboarded follower can
    actually join the consensus."""
    from fabric_tpu.orderer.multichannel import Registrar

    org1 = generate_org("org1.confchg", "Org1MSP")
    oorg = generate_org("orderer.confchg", "OrdererMSP")
    p1, p2 = _free_ports(2)
    gblock = genesis_block(_profile(org1, oorg, [p1]), CHANNEL)
    grown = genesis_block(_profile(org1, oorg, [p1, p2]), CHANNEL)

    registrar = Registrar(
        str(tmp_path / "orderer"),
        signer=SigningIdentity(oorg.peers[0]),
        raft_node_id=1,
    )
    support = registrar.join_channel(gblock)
    chain = support.chain
    # single-node raft: becomes leader on first tick
    deadline = time.time() + 5
    while chain.node.role != "leader" and time.time() < deadline:
        chain.tick()
    assert chain.node.role == "leader"
    assert chain.node.peers == {1}

    # drive the REAL path: configure() -> raft commit -> _apply_entry
    # -> on_config_block -> bridge (including the re-entrant
    # propose->pump->apply the writer-height guard must absorb)
    env = protoutil.get_envelope_from_block_data(grown.data.data[0])
    chain.configure(env)
    deadline = time.time() + 5
    while chain.node.peers != {1, 2} and time.time() < deadline:
        chain.tick()
    assert chain.node.peers == {1, 2}
    assert chain.height == 2  # genesis + the committed config block
    from fabric_tpu.orderer.follower import consenter_addresses

    assert len(consenter_addresses(support.bundle)) == 2
