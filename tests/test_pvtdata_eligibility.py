"""Per-requester private-data eligibility on the gossip pull path
(reference gossip/privdata/pull.go:614 filterNotEligible / :662
isEligibleByLatestConfig): a served digest requires the REQUESTER's
authenticated identity to satisfy that collection's member-orgs policy.
An ineligible org's pull is refused."""

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.gossip.pvtdata import PvtDataHandler, _request_signing_bytes
from fabric_tpu.ledger.collections import (
    CollectionAccess,
    build_collection_config_package,
)
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.protos import gossip_pb2

PROVIDER = SoftwareProvider()
CHANNEL = "pvtelig"


class _Transient:
    def persist(self, *a):
        pass


@pytest.fixture(scope="module")
def world():
    org1 = generate_org("org1.pvtelig", "Org1MSP")
    org2 = generate_org("org2.pvtelig", "Org2MSP")
    mgr = MSPManager(
        [org1.msp(provider=PROVIDER), org2.msp(provider=PROVIDER)]
    )
    pkg = build_collection_config_package(
        [{"name": "secret", "policy": "OR('Org1MSP.member')"}]
    )
    access = CollectionAccess(pkg.config[0].static_collection_config)

    signers = {
        "org1": SigningIdentity(org1.peers[0], PROVIDER),
        "org2": SigningIdentity(org2.peers[0], PROVIDER),
    }
    certstore = {
        b"org1-peer": signers["org1"].serialize(),
        b"org2-peer": signers["org2"].serialize(),
    }

    def verify_member_sig(identity, data, sig):
        try:
            ident, msp = mgr.deserialize_identity(identity)
            msp.validate(ident)
            ident.verify(data, sig)
            return True
        except Exception:  # noqa: BLE001
            return False

    def requester_eligible(ns, coll, identity):
        if (ns, coll) != ("mycc", "secret"):
            return False
        ident, msp = mgr.deserialize_identity(identity)
        return access.is_member(ident, msp)

    handler = PvtDataHandler(
        CHANNEL,
        _Transient(),
        lambda blk, tx, ns, coll: b"the-private-rwset",
        resolve_identity=certstore.get,
        verify_member_sig=verify_member_sig,
        requester_eligible=requester_eligible,
    )
    return {"handler": handler, "signers": signers}


def _request(pki_id=b"", signer=None, tamper=False, channel=CHANNEL, nonce=None):
    import secrets

    msg = gossip_pb2.GossipMessage()
    msg.channel = CHANNEL
    d = msg.private_req.digests.add()
    d.namespace = "mycc"
    d.collection = "secret"
    d.block_seq = 3
    d.seq_in_block = 0
    if pki_id:
        msg.private_req.pki_id = pki_id
    if signer is not None:
        msg.private_req.nonce = nonce or secrets.token_bytes(24)
        msg.private_req.signature = signer.sign(
            _request_signing_bytes(msg.private_req, channel)
        )
        if tamper:
            d2 = msg.private_req.digests.add()
            d2.namespace = "mycc"
            d2.collection = "secret"
            d2.block_seq = 4
            d2.seq_in_block = 0
    return msg


def test_eligible_org_is_served(world):
    resp = world["handler"].handle(
        _request(b"org1-peer", world["signers"]["org1"])
    )
    assert resp is not None
    assert len(resp.private_res.elements) == 1
    assert bytes(resp.private_res.elements[0].payload) == b"the-private-rwset"


def test_ineligible_org_pull_is_refused(world):
    # Org2 authenticates fine but fails the collection's member-orgs
    # policy (OR Org1MSP.member) -> nothing served
    resp = world["handler"].handle(
        _request(b"org2-peer", world["signers"]["org2"])
    )
    assert resp is None


def test_unsigned_request_refused(world):
    assert world["handler"].handle(_request(b"org1-peer")) is None
    assert world["handler"].handle(_request()) is None


def test_unknown_pki_id_refused(world):
    resp = world["handler"].handle(
        _request(b"nobody", world["signers"]["org1"])
    )
    assert resp is None


def test_tampered_digests_refused(world):
    # signature covers the digest list; adding a digest after signing
    # must invalidate the request
    resp = world["handler"].handle(
        _request(b"org1-peer", world["signers"]["org1"], tamper=True)
    )
    assert resp is None


def test_wrong_org_signature_refused(world):
    # org2's signature presented under org1's pki_id
    msg = _request(b"org1-peer", world["signers"]["org2"])
    assert world["handler"].handle(msg) is None


def test_replayed_request_refused(world):
    # the identical signed request served once is never served again
    # (nonce consumed); a fresh nonce from the same org works
    msg = _request(b"org1-peer", world["signers"]["org1"])
    assert world["handler"].handle(msg) is not None
    assert world["handler"].handle(msg) is None
    again = _request(b"org1-peer", world["signers"]["org1"])
    assert world["handler"].handle(again) is not None


def test_cross_channel_signature_refused(world):
    # a request signed for another channel's handler must not validate
    # here (channel id is bound into the signed bytes)
    msg = _request(b"org1-peer", world["signers"]["org1"], channel="otherchan")
    assert world["handler"].handle(msg) is None
