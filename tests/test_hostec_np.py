"""Differential tests: the numpy limb-matrix EC tier (crypto/hostec_np)
vs the CPython hostec engine and the Python-int oracle.

hostec_np is the second rung of the host EC backend ladder (fastec ->
hostec_np -> hostec -> p256).  These tests pin its VALID/INVALID mask
bit-exactly to hostec (which is itself pinned to the oracle by
test_hostec.py) across adversarial lanes, drive the exceptional-lane
machinery (P = +-Q, infinity results) both end-to-end and at the
kernel level, prove the shared-memory sharding is order-preserving,
and chain dense-limb / 4m-edge operands through the Montgomery kernels
against the Python-int oracle exactly like test_bignum.py does for the
device kernels.  The whole module skips cleanly when numpy is absent
(the ladder itself must degrade, not break — covered below via a
monkeypatched HAVE_NUMPY).
"""

import hashlib
import secrets
import subprocess
import sys

import pytest

np = pytest.importorskip("numpy")

from fabric_tpu.common import p256
from fabric_tpu.crypto import der, hostec
from fabric_tpu.crypto import hostec_np as hn
from fabric_tpu.crypto.bccsp import (
    ECDSAPublicKey,
    SoftwareProvider,
    ec_backend_name,
    select_ec_backend,
)

N = p256.N
P = p256.P
G = p256.GENERATOR


def _digest(tag, i):
    return hashlib.sha256(b"%s %d" % (tag, i)).digest()


@pytest.fixture(scope="module")
def keypairs():
    return [hostec.generate_keypair() for _ in range(4)]


def _signed_lane(keypairs, tag, i):
    kp = keypairs[i % len(keypairs)]
    d = _digest(tag, i)
    r, s = hostec.sign_digest(kp.priv, d)
    return kp.pub, d, r, s


def _hostec_mask(lanes):
    return hostec.verify_parsed_batch(lanes)


# ---------------------------------------------------------------------------
# Montgomery kernel units: oracle differential + near-overflow regression
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("modulus", [p256.P, p256.N], ids=["P", "N"])
def test_mont_kernels_match_int_oracle(modulus):
    ctx = hn._ctx(modulus)
    field = hn._Field(ctx)
    rinv = pow(hn.R_MONT, -1, modulus)
    rng = secrets.SystemRandom()
    xs = [rng.randrange(2 * modulus) for _ in range(29)] + [0, 1, modulus]
    ys = [rng.randrange(2 * modulus) for _ in range(29)] + [modulus, 0, 1]
    a = hn.limbs13_to_pairs(hn.ints_to_limbs13(xs))
    b = hn.limbs13_to_pairs(hn.ints_to_limbs13(ys))
    out = field.kmul(a.copy(), b.copy())
    for i, (x, y) in enumerate(zip(xs, ys)):
        v = hn._pairs_to_int(out[:, i])
        assert v < 2 * modulus
        assert v % modulus == x * y * rinv % modulus
    sq = field.sqr(field.fe(a.copy(), 2, hn.PAIR_MASK))
    for i, x in enumerate(xs):
        assert (
            hn._pairs_to_int(sq.limbs[:, i]) % modulus
            == x * x * rinv % modulus
        )


@pytest.mark.parametrize("modulus", [p256.P, p256.N], ids=["P", "N"])
def test_reference_kernels_bit_exact_with_optimized(modulus):
    """The fabflow limb-tier proof runs over the plain-operator
    reference kernels; this pins the workspace-optimized kernels (whose
    out=/buffer plumbing the interval domain cannot track) bit-exact
    against them, so the mechanized bound transfers."""
    ctx = hn._ctx(modulus)
    field = hn._Field(ctx)
    rng = secrets.SystemRandom()
    xs = [rng.randrange(2 * modulus) for _ in range(23)]
    ys = [rng.randrange(2 * modulus) for _ in range(23)]
    a = hn.limbs13_to_pairs(hn.ints_to_limbs13(xs))
    b = hn.limbs13_to_pairs(hn.ints_to_limbs13(ys))
    opt = field.kmul(a.copy(), b.copy())
    if ctx.p256_bias is not None:
        ref = hn._mul_kernel_ref_p256(a.copy(), b.copy(), ctx.p256_bias)
    else:
        ref = hn._mul_kernel_ref(a.copy(), b.copy(), ctx.m_col, ctx.m0inv)
    assert (ref == opt).all()


def test_mont_mul_near_overflow_boundary():
    """test_bignum.py's dense-limb regression, at the pair radix: dense
    0x1fff-limb operands and 2m-edge values chained through 8 squarings
    stay bit-exact with the Python-int oracle.  If someone widens the
    L32/L4 contracts, drops the complement-fold bias, or breaks a
    carry, this chain wraps and diverges."""
    for modulus in (p256.P, p256.N):
        ctx = hn._ctx(modulus)
        field = hn._Field(ctx)
        rinv = pow(hn.R_MONT, -1, modulus)
        dense = (1 << 255) - 1  # nineteen 0x1fff limbs + 0xff top
        edge = 2 * modulus - 1  # the laxest canonical-value input
        ops = [dense, edge, modulus - 1, dense % modulus]
        arr = hn.limbs13_to_pairs(hn.ints_to_limbs13(ops))
        want = list(ops)
        got = arr
        for _ in range(8):
            got = field.sqr(field.fe(got.copy(), 2, hn.PAIR_MASK)).limbs
            want = [(x * x * rinv) % modulus for x in want]
            vals = [
                hn._pairs_to_int(got[:, i]) % modulus
                for i in range(len(ops))
            ]
            assert vals == want


def test_p256_redc_terms_reconstruct_p():
    """The hardcoded shift decomposition in _redc_rows_p256 IS p."""
    recon = -1
    for coff, sh, sign in hn._P256_REDC_TERMS:
        recon += sign << (hn.PAIR_BITS * coff + sh)
    assert recon == p256.P
    ctx = hn._ctx(p256.P)
    assert ctx.p256_bias is not None
    assert int(ctx.p256_bias.max()) <= hn.PAIR_MASK  # canonical bias


def test_tree_batch_inversion():
    """Per-lane inverses via the lane-pairing tree, zero lanes masked
    to zero — including widths that exercise odd tails at every level."""
    ctx = hn._ctx(p256.P)
    field = hn._Field(ctx)
    rinv = ctx.rinv
    for lanes_n in (1, 2, 3, 7, 16, 33):
        xs = [secrets.randbelow(p256.P) for _ in range(lanes_n)]
        if lanes_n > 2:
            xs[1] = 0  # a zero lane must not poison the tree
        arr = hn.limbs13_to_pairs(hn.ints_to_limbs13(xs))
        inv = hn._invert_lanes(field, field.fe(arr, 2, hn.PAIR_MASK))
        for i, x in enumerate(xs):
            got = (hn._pairs_to_int(inv.limbs[:, i]) * rinv) % p256.P
            want = 0 if x == 0 else pow(
                (x * rinv) % p256.P, -1, p256.P
            )
            assert got == want, (lanes_n, i)


# ---------------------------------------------------------------------------
# Differential fuzz vs the hostec mask
# ---------------------------------------------------------------------------


def test_fuzz_mask_matches_hostec(keypairs):
    """Mixed batch: valid, bit-flipped r, bit-flipped s, wrong digest,
    high-S — one matrix pass, bit-exact with hostec (itself pinned to
    the oracle)."""
    import random

    rng = random.Random(0x417)
    lanes = []
    for i in range(48):
        pub, d, r, s = _signed_lane(keypairs, b"fuzznp", i)
        kind = i % 5
        if kind == 1:
            r ^= 1 << rng.randrange(256)
        elif kind == 2:
            s ^= 1 << rng.randrange(256)
        elif kind == 3:
            d = _digest(b"other", i)
        elif kind == 4:
            s = N - s  # high-S is valid at this layer
        lanes.append((pub, d, r, s))
    assert hn.verify_parsed_batch(lanes) == _hostec_mask(lanes)


def test_rs_boundary_values(keypairs):
    pub, d, r, s = _signed_lane(keypairs, b"edgenp", 0)
    edges = [0, 1, N - 1, N, N + 1]
    lanes = [(pub, d, e, s) for e in edges]
    lanes += [(pub, d, r, e) for e in edges]
    lanes.append((pub, d, r, s))
    got = hn.verify_parsed_batch(lanes)
    assert got == _hostec_mask(lanes)
    assert got[-1] is True
    assert not any(got[:-1])


def test_bad_public_keys(keypairs):
    """Off-curve, out-of-range and identity (None) keys verify False
    and never raise — mixed into a batch with healthy lanes."""
    pub, d, r, s = _signed_lane(keypairs, b"badkeynp", 0)
    x, y = pub
    lanes = [
        ((x, (y + 1) % P), d, r, s),
        ((P, y), d, r, s),
        ((x, P + y), d, r, s),
        (None, d, r, s),
        (pub, d, r, s),
    ]
    got = hn.verify_parsed_batch(lanes)
    assert got == [False, False, False, False, True]
    assert got == _hostec_mask(lanes)


def test_batch_sizes(keypairs):
    """Sizes around window/shard seams; every 3rd lane corrupted."""
    for size in (1, 2, 31, 33, 97):
        lanes = []
        expect = []
        for i in range(size):
            pub, d, r, s = _signed_lane(keypairs, b"sz%d" % size, i)
            if i % 3 == 1:
                s ^= 2
                expect.append(False)
            else:
                expect.append(True)
            lanes.append((pub, d, r, s))
        assert hn.verify_parsed_batch(lanes) == expect, size


# ---------------------------------------------------------------------------
# Exceptional lanes: P = +-Q, infinity
# ---------------------------------------------------------------------------


def test_exceptional_madd_paths_kernel_level():
    """_madd_vec on crafted equal/negated/infinity operands takes the
    wholesale-detect + scalar-patch path and matches hostec._madd1."""
    field = hn._Field(hn._ctx(P))
    kp = hostec.generate_keypair()
    five = p256.scalar_mult(5, kp.pub)
    lanes_n = 3
    rinv = field.ctx.rinv

    def mk(v):
        arr = hn.limbs13_to_pairs(
            hn.ints_to_limbs13([(v * hn.R_MONT) % P] * lanes_n)
        )
        return field.fe(arr, 1, hn.PAIR_MASK)

    X, Y, Z = mk(five[0]), mk(five[1]), mk(1)
    # P == Q: doubles through the patch
    ax, ay = mk(five[0]), mk(five[1])
    X3, Y3, Z3, exc = hn._madd_vec(field, X, Y, Z, ax, ay)
    assert exc.all()
    X3, Y3, Z3 = hn._patch_exceptional(
        field, exc, (X, Y, Z), X3, Y3, Z3, ax, ay
    )
    want = hostec._dbl1(five[0], five[1], 1)
    got = tuple(
        (hn._pairs_to_int(field.carried(v).limbs[:, 0]) * rinv) % P
        for v in (X3, Y3, Z3)
    )
    zi = pow(want[2], -1, P)
    gzi = pow(got[2], -1, P)
    assert (
        got[0] * gzi * gzi % P == want[0] * zi * zi % P
    )
    # P == -Q: collapses to infinity, recorded via inf_out
    ay_neg = mk(P - five[1])
    inf = np.zeros(lanes_n, dtype=bool)
    X3, Y3, Z3, exc = hn._madd_vec(field, X, Y, Z, ax, ay_neg)
    assert exc.all()
    hn._patch_exceptional(
        field, exc, (X, Y, Z), X3, Y3, Z3, ax, ay_neg, inf_out=inf
    )
    assert inf.all()


def test_exceptional_lanes_end_to_end():
    """Crafted signatures drive the Horner loop through P = +-Q and an
    infinity result with pub = G (priv = 1): u2 = 16 places 16*Q as the
    final Q-add from infinity, u1 = 17 then collides the final G-add
    with the 17*Q accumulator (P = Q since Q = G); u1 = n - u2 makes
    u1*G + u2*Q the identity.  The masks must still match hostec lane
    for lane."""
    lanes = []
    # u1 = e/s, u2 = r/s; with s = 1: e = u1, r = u2 (r must be in
    # [1, n), e rides the digest bytes directly)
    crafts = [
        (17, 16),          # final G-add hits P == Q
        (N - 5, 5),        # result is the identity (infinity)
        (N - 16, 16),      # identity again, different window pattern
        (1, 1),            # plain tiny scalars
    ]
    for u1, u2 in crafts:
        digest = int(u1 % N).to_bytes(32, "big")
        lanes.append((G, digest, u2, 1))
    got = hn.verify_parsed_batch(lanes)
    want = _hostec_mask(lanes)
    assert got == want


def test_signed_digit_negative_windows(keypairs):
    """Scalars dense in 0x1f windows exercise the negated-table path
    (wNAF digits < 0) — craft u2 ≡ pattern via r = u2 * s mod n."""
    kp = keypairs[0]
    lanes = []
    for pat in (
        int("11111" * 51, 2),  # alternating small digits
        (1 << 256) % N,
        N - 1,
        int("1" * 255, 2) % N,  # all-ones: every digit recodes signed
    ):
        d = _digest(b"negwin", pat & 0xFFFF)
        r, s = hostec.sign_digest(kp.priv, d)
        # replace r so u2 = r/s becomes the pattern: r' = pat * s mod n
        r2 = (pat * s) % N
        if r2 == 0:
            continue
        lanes.append((kp.pub, d, r2, s))
    assert hn.verify_parsed_batch(lanes) == _hostec_mask(lanes)


# ---------------------------------------------------------------------------
# Shared-memory sharding
# ---------------------------------------------------------------------------


def test_sharded_is_order_preserving(keypairs, monkeypatch):
    """A pool-sized batch sharded across 2 workers through ONE
    shared-memory block returns the same mask, in the same order, as
    the in-process pass."""
    monkeypatch.setenv("FABRIC_TPU_HOSTEC_NP_PROCS", "2")
    monkeypatch.setenv("FABRIC_TPU_HOSTEC_NP_MIN_LANES", "64")
    monkeypatch.setattr(hn, "MIN_POOL_LANES", 128)
    monkeypatch.setattr(hn, "MIN_SHARD_LANES", 64)
    hn.shutdown_pool()
    lanes = []
    for i in range(131):
        pub, d, r, s = _signed_lane(keypairs, b"shardnp", i)
        if i % 7 == 3:
            r ^= 4
        lanes.append((pub, d, r, s))
    try:
        resolver = hn.verify_parsed_batch_sharded(lanes)
        sharded = resolver()
        # double resolve must return the memoized verdicts — the shm
        # mapping is gone after the first call, and re-reading the
        # verdict view over it would crash the process
        assert resolver() == sharded
    finally:
        hn.shutdown_pool()
    assert sharded == hn.verify_parsed_batch(lanes)


def test_small_batches_delegate_to_hostec(keypairs, monkeypatch):
    """Below NP_MIN_LANES the sharded entrypoint rides hostec (the
    matrix engine's fixed cost loses on small batches)."""
    calls = []
    orig = hostec.verify_parsed_batch_sharded

    def spy(lanes):
        calls.append(len(lanes))
        return orig(lanes)

    monkeypatch.setattr(hostec, "verify_parsed_batch_sharded", spy)
    lanes = [_signed_lane(keypairs, b"tiny", i) for i in range(8)]
    assert hn.verify_parsed_batch_sharded(lanes)() == [True] * 8
    assert calls == [8]


# ---------------------------------------------------------------------------
# Ladder / provider integration + numpy-absent degradation
# ---------------------------------------------------------------------------


@pytest.fixture()
def np_backend():
    before = ec_backend_name()
    select_ec_backend("hostec_np")
    yield
    select_ec_backend(before)


def test_software_provider_batch_on_hostec_np(np_backend, keypairs):
    sw = SoftwareProvider()
    assert sw.describe_backend() == "sw:hostec_np"
    keys, sigs, digests, expect = [], [], [], []
    for i in range(12):
        kp = keypairs[i % len(keypairs)]
        d = _digest(b"provnp", i)
        r, s = hostec.sign_digest(kp.priv, d)
        if i % 3 == 2:
            d = _digest(b"provnp!", i)
            expect.append(False)
        else:
            expect.append(True)
        keys.append(ECDSAPublicKey(*kp.pub))
        sigs.append(der.marshal_signature(r, s))
        digests.append(d)
    # DER-garbage lane fails the precheck and comes back False
    keys.append(keys[0])
    sigs.append(b"\x30\x03\x02\x01\x01")
    digests.append(digests[0])
    expect.append(False)
    assert sw.batch_verify(keys, sigs, digests) == expect


def test_scalar_api_delegates_to_hostec(keypairs):
    """verify_digest/sign_digest/scalar_base_mult ride hostec's scalar
    paths (bit-identical semantics, no matrix overhead per lane)."""
    kp = keypairs[0]
    d = _digest(b"scalarnp", 0)
    r, s = hn.sign_digest(kp.priv, d)
    assert s <= p256.HALF_N
    assert hn.verify_digest(kp.pub, d, r, s)
    assert hn.scalar_base_mult(7) == p256.scalar_mult(7, G)


def test_auto_ladder_skips_np_tier_without_numpy(monkeypatch):
    """With numpy 'absent' (HAVE_NUMPY False), auto lands on hostec and
    an explicit hostec_np pin raises ImportError — degrade loudly in
    the log, never silently for a pinned config."""
    before = ec_backend_name()
    monkeypatch.setattr(hn, "HAVE_NUMPY", False)
    try:
        import fabric_tpu.crypto.fastec  # noqa: F401

        pytest.skip("cryptography installed: auto selects fastec here")
    except ImportError:
        pass
    try:
        mod = select_ec_backend("auto")
        assert mod is hostec
        with pytest.raises(ImportError):
            select_ec_backend("hostec_np")
    finally:
        monkeypatch.setattr(hn, "HAVE_NUMPY", True)
        select_ec_backend(before)


def test_module_imports_without_numpy_subprocess():
    """The module itself (and the ladder around it) must import with
    numpy genuinely blocked — the guarded-import discipline the
    collect gate relies on."""
    code = (
        "import sys\n"
        "sys.modules['numpy'] = None\n"  # import numpy -> ImportError
        "import fabric_tpu.crypto.hostec_np as hn\n"
        "assert not hn.HAVE_NUMPY\n"
        "from fabric_tpu.crypto.bccsp import select_ec_backend\n"
        "mod = select_ec_backend('auto')\n"
        "assert mod.__name__.rsplit('.', 1)[-1] in "
        "('fastec', 'hostec'), mod.__name__\n"
        "print('ok')\n"
    )
    res = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr
    assert "ok" in res.stdout


def test_factory_accepts_and_warns(monkeypatch):
    """BCCSP.SW.ECBackend: hostec_np accepted; unknown values warn and
    leave the pinned backend alone (never raise)."""
    from fabric_tpu.crypto import factory

    before = ec_backend_name()
    try:
        factory.provider_from_config(
            {"Default": "SW", "SW": {"ECBackend": "hostec_np"}}
        )
        assert ec_backend_name() == "hostec_np"
        factory.provider_from_config(
            {"Default": "SW", "SW": {"ECBackend": "no-such-tier"}}
        )
        assert ec_backend_name() == "hostec_np"  # pin left alone
    finally:
        select_ec_backend(before)


def test_verify_batcher_routes_through_hostec_np(np_backend, keypairs):
    """VerifyBatcher -> SoftwareProvider.batch_verify_async ->
    hostec_np sharded engine, order-preserving per request."""
    from fabric_tpu.parallel.batcher import VerifyBatcher

    calls = []
    orig = hn.verify_parsed_batch_sharded

    def spy(lanes):
        calls.append(len(lanes))
        return orig(lanes)

    sw = SoftwareProvider()
    b = VerifyBatcher(sw, linger_s=0.02)
    try:
        hn.verify_parsed_batch_sharded = spy
        reqs = []
        for i in range(3):
            keys, sigs, digests, expect = [], [], [], []
            for j in range(3 + i):
                kp = keypairs[j % len(keypairs)]
                d = _digest(b"vbnp%d" % i, j)
                r, s = hostec.sign_digest(kp.priv, d)
                keys.append(ECDSAPublicKey(*kp.pub))
                sigs.append(der.marshal_signature(r, s))
                digests.append(d)
                expect.append(True)
            reqs.append((keys, sigs, digests, expect))
        resolvers = [b.submit(k, s, d) for k, s, d, _ in reqs]
        for resolver, (_k, _s, _d, expect) in zip(resolvers, reqs):
            assert resolver() == expect
    finally:
        hn.verify_parsed_batch_sharded = orig
        b.stop()
    assert sum(calls) == sum(3 + i for i in range(3))


@pytest.mark.slow
def test_batch_1024_differential_slow(keypairs):
    lanes = []
    for i in range(1024):
        pub, d, r, s = _signed_lane(keypairs, b"kilonp", i)
        if i % 4 == 3:
            s ^= 1 << (i % 250)
        lanes.append((pub, d, r, s))
    assert hn.verify_parsed_batch(lanes) == _hostec_mask(lanes)
