"""Private data: pvtdata store (BTL expiry, missing-data, backfill),
collection configs/access, and the ledger commit integration with
hash-checked cleartext writes (reference core/ledger/pvtdatastorage,
core/common/privdata, gossip/privdata/coordinator.go)."""

import hashlib
import os

import pytest

from conftest import requires_crypto
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.collections import (
    CollectionStore,
    NoSuchCollectionError,
    build_collection_config_package,
)
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.pvtdatastore import MissingEntry, PvtDataStore, PvtEntry
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.protos import common_pb2, kv_rwset_pb2, protoutil
from fabric_tpu.validation.txflags import TxValidationCode, ValidationFlags

PROVIDER = SoftwareProvider()


def kvrwset_bytes(writes):
    kv = kv_rwset_pb2.KVRWSet()
    for key, value in writes:
        w = kv.writes.add()
        w.key = key
        if value is None:
            w.is_delete = True
        else:
            w.value = value
    return kv.SerializeToString()


# ---------------- PvtDataStore ----------------


def test_pvtdata_store_roundtrip_and_recovery(tmp_path):
    path = str(tmp_path / "pvt")
    store = PvtDataStore(path)
    e0 = PvtEntry(0, "mycc", "secret", kvrwset_bytes([("k", b"v")]))
    store.commit(0, [e0], [MissingEntry(1, "mycc", "other")])
    store.commit(1, [])
    assert store.get_pvt_data(0, 0) == [e0]
    assert store.last_committed_block == 1
    store.close()

    again = PvtDataStore(path)
    assert again.get_pvt_data(0, 0) == [e0]
    assert again.get_missing_pvt_data() == {
        0: [MissingEntry(1, "mycc", "other")]
    }
    assert again.last_committed_block == 1


def test_pvtdata_store_rejects_out_of_order(tmp_path):
    store = PvtDataStore(str(tmp_path / "pvt"))
    store.commit(0, [])
    with pytest.raises(ValueError):
        store.commit(0, [])


def test_pvtdata_store_btl_expiry(tmp_path):
    store = PvtDataStore(
        str(tmp_path / "pvt"), btl_policy=lambda ns, coll: 2
    )
    e = PvtEntry(0, "mycc", "secret", kvrwset_bytes([("k", b"v")]))
    store.commit(0, [e])
    store.commit(1, [])
    store.commit(2, [])
    assert store.get_pvt_data(0, 0) == [e]  # 0 + 2 >= 2: still alive
    store.commit(3, [])  # 0 + 2 < 3: expired
    assert store.get_pvt_data(0, 0) == []


def test_pvtdata_store_backfill_clears_missing(tmp_path):
    store = PvtDataStore(str(tmp_path / "pvt"))
    store.commit(0, [], [MissingEntry(0, "mycc", "secret")])
    assert 0 in store.get_missing_pvt_data()
    late = PvtEntry(0, "mycc", "secret", kvrwset_bytes([("k", b"v")]))
    store.commit_pvt_data_of_old_blocks(0, [late])
    assert store.get_missing_pvt_data() == {}
    assert store.get_pvt_data(0, 0) == [late]


def test_pvtdata_backfill_survives_restart(tmp_path):
    """Regression: backfill records must ACCUMULATE on recovery (not
    replace the original entries) and cleared missing markers must stay
    cleared after restart."""
    path = str(tmp_path / "pvt")
    store = PvtDataStore(path)
    a = PvtEntry(0, "mycc", "collA", kvrwset_bytes([("ka", b"va")]))
    store.commit(0, [a], [MissingEntry(1, "mycc", "collB")])
    b = PvtEntry(1, "mycc", "collB", kvrwset_bytes([("kb", b"vb")]))
    store.commit_pvt_data_of_old_blocks(0, [b])
    assert store.get_missing_pvt_data() == {}
    assert sorted(e.collection for e in store.get_pvt_data_by_block(0)) == [
        "collA",
        "collB",
    ]
    store.close()

    again = PvtDataStore(path)
    assert sorted(e.collection for e in again.get_pvt_data_by_block(0)) == [
        "collA",
        "collB",
    ]
    assert again.get_missing_pvt_data() == {}


def test_pvtdata_recovery_drops_torn_tail(tmp_path):
    """Regression: a partially-written final record is discarded, not
    accepted with a truncated field."""
    path = str(tmp_path / "pvt")
    store = PvtDataStore(path)
    good = PvtEntry(0, "mycc", "c", kvrwset_bytes([("k", b"v")]))
    store.commit(0, [good])
    store.close()
    size_after_good = os.path.getsize(path)
    # simulate a crash mid-append: a torn record (valid header-checksummed
    # length prefix, body cut off short of the claimed 1000 bytes)
    from fabric_tpu.ledger.blockstore import frame_header

    with open(path, "ab") as f:
        f.write(frame_header(1000) + b"partial body")
    again = PvtDataStore(path)
    assert again.get_pvt_data(0, 0) == [good]
    assert os.path.getsize(path) == size_after_good  # tail trimmed


def test_pvtdata_recovery_rejects_absurd_record_counts(tmp_path):
    """Regression: _load_record sized its entry/missing loops off u32
    counts read from the record verbatim — a crc-valid but corrupt or
    hostile record could drive a 2**31-iteration loop. Counts larger
    than the record body (each entry consumes >= 4 bytes) are now
    refused loudly before any per-count work."""
    import struct
    import zlib

    from fabric_tpu.ledger.blockstore import LedgerCorruptionError, frame_header

    path = str(tmp_path / "pvt")
    store = PvtDataStore(path)
    good = PvtEntry(0, "mycc", "c", kvrwset_bytes([("k", b"v")]))
    store.commit(0, [good])
    store.close()
    # a fully crc-framed record whose entry count dwarfs its body: the
    # count bound raises ValueError, which recovery's fail-closed
    # discipline surfaces as strict-mode corruption refusal
    body = struct.pack("<QII", 1, 2**31, 0)
    with open(path, "ab") as f:
        f.write(frame_header(len(body)) + body)
        f.write(struct.pack("<I", zlib.crc32(body)))
    with pytest.raises(LedgerCorruptionError, match="does not parse"):
        PvtDataStore(path)
    # the missing-marker count is bounded the same way
    body2 = struct.pack("<QII", 1, 0, 2**31)
    store2 = PvtDataStore.__new__(PvtDataStore)
    with pytest.raises(ValueError, match="exceed"):
        store2._load_record(body2)


def test_pvtdata_rollback_rewinds_store(tmp_path):
    store = PvtDataStore(str(tmp_path / "pvt"))
    e0 = PvtEntry(0, "mycc", "c", kvrwset_bytes([("k0", b"v0")]))
    e1 = PvtEntry(0, "mycc", "c", kvrwset_bytes([("k1", b"v1")]))
    store.commit(0, [e0])
    store.commit(1, [e1])
    store.rollback_to(1)
    assert store.last_committed_block == 0
    assert store.get_pvt_data_by_block(1) == []
    # new commits at the rolled-back height work again
    store.commit(1, [e1])
    assert store.last_committed_block == 1
    store.close()
    again = PvtDataStore(str(tmp_path / "pvt"))
    assert again.last_committed_block == 1


# ---------------- collections ----------------


@pytest.fixture(scope="module")
def orgs():
    return (
        generate_org("org1.example.com", "Org1MSP"),
        generate_org("org2.example.com", "Org2MSP"),
    )


@requires_crypto
def test_collection_store_and_membership(orgs):
    org1, org2 = orgs
    pkg = build_collection_config_package(
        [
            {
                "name": "secret",
                "policy": "OR('Org1MSP.member')",
                "block_to_live": 5,
                "member_only_read": True,
            }
        ]
    )
    store = CollectionStore(
        lambda ns: pkg.SerializeToString() if ns == "mycc" else b""
    )
    access = store.collection("mycc", "secret")
    assert access.block_to_live == 5
    assert access.member_only_read

    msp1 = org1.msp(provider=PROVIDER)
    msp2 = org2.msp(provider=PROVIDER)
    id1 = msp1.deserialize_identity(
        protoutil.serialize_identity("Org1MSP", org1.peers[0].cert_pem)
    )
    id2 = msp2.deserialize_identity(
        protoutil.serialize_identity("Org2MSP", org2.peers[0].cert_pem)
    )
    assert access.is_member(id1, msp1)
    assert not access.is_member(id2, msp2)

    assert store.has_collection("mycc", "secret")
    assert not store.has_collection("mycc", "nope")
    with pytest.raises(NoSuchCollectionError):
        store.collection("othercc", "secret")
    assert store.btl_policy()("mycc", "secret") == 5
    assert store.btl_policy()("mycc", "unknown") == 0


# ---------------- ledger commit integration ----------------


def make_block_with_pvt(number, prev_hash, tx_rwset_bytes):
    """A block with one fake envelope whose rwset the test injects via the
    rwsets= parameter of commit (parse path is covered by e2e tests)."""
    block = protoutil.new_block(number, prev_hash)
    block.data.data.append(b"\x00")  # placeholder envelope
    protoutil.seal_block(block)
    protoutil.init_block_metadata(block)
    flags = ValidationFlags(1, TxValidationCode.VALID)
    block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] = flags.tobytes()
    return block


def pvt_rwset_for(key, value):
    kh = hashlib.sha256(key.encode()).digest()
    vh = hashlib.sha256(value).digest()
    rwset = rw.TxRwSet(
        (
            rw.NsRwSet(
                "mycc",
                coll_hashed=(
                    rw.CollHashedRwSet(
                        "secret",
                        hashed_writes=(rw.KVWriteHash(kh, False, vh),),
                    ),
                ),
            ),
        )
    )
    return rwset


def test_ledger_commit_applies_hash_checked_pvt_data(tmp_path):
    ledger = KVLedger(str(tmp_path), "ch")
    rwset = pvt_rwset_for("k1", b"top-secret")
    block = make_block_with_pvt(0, b"", rwset)
    ledger.commit(
        block,
        rwsets=[rwset],
        pvt_data={(0, "mycc", "secret"): kvrwset_bytes([("k1", b"top-secret")])},
    )
    assert ledger.get_private_data("mycc", "secret", "k1") == b"top-secret"
    # hashed state is on-block as usual
    kh = hashlib.sha256(b"k1").digest()
    assert ledger.state_db.get_hashed_state("mycc", "secret", kh) is not None
    # pvt store has it
    assert len(ledger.pvt_store.get_pvt_data(0, 0)) == 1


def test_ledger_commit_rejects_hash_mismatch(tmp_path):
    ledger = KVLedger(str(tmp_path), "ch")
    rwset = pvt_rwset_for("k1", b"real-value")
    block = make_block_with_pvt(0, b"", rwset)
    with pytest.raises(ValueError):
        ledger.commit(
            block,
            rwsets=[rwset],
            pvt_data={(0, "mycc", "secret"): kvrwset_bytes([("k1", b"forged")])},
        )


def test_ledger_recovery_replays_pvt_state(tmp_path):
    ledger = KVLedger(str(tmp_path), "ch")
    rwset = pvt_rwset_for("k1", b"persist-me")
    block = make_block_with_pvt(0, b"", rwset)
    ledger.commit(
        block,
        rwsets=[rwset],
        pvt_data={(0, "mycc", "secret"): kvrwset_bytes([("k1", b"persist-me")])},
    )
    ledger.block_store.close()
    ledger.pvt_store.close()

    # reopen: pvt cleartext state must be rebuilt from the pvt store.
    # NB the placeholder envelope is unparsable, so recovery sees rwset
    # None for the tx — commit with real envelopes is covered in e2e; here
    # we assert the pvt store itself survives.
    again = PvtDataStore(str(tmp_path / "ch.pvtdata"))
    assert len(again.get_pvt_data(0, 0)) == 1


@requires_crypto
def test_channel_pipeline_with_transient_store(tmp_path, orgs):
    """End-to-end: endorse a tx with private data, stage the cleartext in
    the transient store, order, and watch the peer channel assemble +
    commit it (coordinator.go StoreBlock flow)."""
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.gossip.coordinator import TransientStore
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
    from fabric_tpu.msp.identity import MSPManager
    from fabric_tpu.msp.signer import SigningIdentity
    from fabric_tpu.orderer import SoloChain
    from fabric_tpu.orderer.blockcutter import BatchConfig
    from fabric_tpu.peer import Channel
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.validation.validator import (
        ChaincodeDefinition,
        ChaincodeRegistry,
    )

    org1, _ = orgs
    mgr = MSPManager([org1.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("mycc", from_dsl("OR('Org1MSP.member')"))]
    )
    transient = TransientStore()
    peer_channel = Channel(
        "pvtchannel",
        str(tmp_path / "peer"),
        mgr,
        registry,
        PROVIDER,
        transient_store=transient,
        is_eligible=lambda ns, coll: True,
    )
    client = SigningIdentity(org1.users[0], PROVIDER)
    peer = SigningIdentity(org1.peers[0], PROVIDER)

    key, value = "pk", b"private-value"
    kh = hashlib.sha256(key.encode()).digest()
    vh = hashlib.sha256(value).digest()
    rwset = rw.TxRwSet(
        (
            rw.NsRwSet(
                "mycc",
                writes=(rw.KVWrite("pub", False, b"public"),),
                coll_hashed=(
                    rw.CollHashedRwSet(
                        "secret", hashed_writes=(rw.KVWriteHash(kh, False, vh),)
                    ),
                ),
            ),
        )
    )
    bundle = create_proposal(client, "pvtchannel", "mycc", [b"putpvt", b"pk"])
    env = create_signed_tx(
        bundle,
        client,
        [endorse_proposal(bundle, peer, serialize_tx_rwset(rwset))],
    )
    # endorser distributed the cleartext to the transient store
    transient.persist(bundle.tx_id, "mycc", "secret", kvrwset_bytes([(key, value)]))

    blocks = []
    chain = SoloChain(
        "pvtchannel",
        signer=peer,
        batch_config=BatchConfig(max_message_count=1),
        deliver=blocks.append,
    )
    chain.order(env)
    flags = peer_channel.store_block(blocks[0])
    assert flags.is_valid(0)
    assert (
        peer_channel.ledger.get_private_data("mycc", "secret", "pk")
        == value
    )
    assert peer_channel.ledger.get_state("mycc", "pub") == b"public"
    # transient store purged post-commit
    assert transient.get(bundle.tx_id, "mycc", "secret") is None
    # nothing missing
    assert peer_channel.ledger.pvt_store.get_missing_pvt_data() == {}


@requires_crypto
def test_channel_pipeline_records_missing_pvt(tmp_path, orgs):
    """Without transient data or a fetcher, the commit records the gap for
    the reconciler instead of failing."""
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
    from fabric_tpu.msp.identity import MSPManager
    from fabric_tpu.msp.signer import SigningIdentity
    from fabric_tpu.orderer import SoloChain
    from fabric_tpu.orderer.blockcutter import BatchConfig
    from fabric_tpu.peer import Channel
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.validation.validator import (
        ChaincodeDefinition,
        ChaincodeRegistry,
    )

    org1, _ = orgs
    mgr = MSPManager([org1.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("mycc", from_dsl("OR('Org1MSP.member')"))]
    )
    peer_channel = Channel(
        "pvtchannel2",
        str(tmp_path / "peer"),
        mgr,
        registry,
        PROVIDER,
        is_eligible=lambda ns, coll: True,
    )
    client = SigningIdentity(org1.users[0], PROVIDER)
    peer = SigningIdentity(org1.peers[0], PROVIDER)
    kh = hashlib.sha256(b"k").digest()
    rwset = rw.TxRwSet(
        (
            rw.NsRwSet(
                "mycc",
                coll_hashed=(
                    rw.CollHashedRwSet(
                        "secret",
                        hashed_writes=(
                            rw.KVWriteHash(kh, False, hashlib.sha256(b"v").digest()),
                        ),
                    ),
                ),
            ),
        )
    )
    bundle = create_proposal(client, "pvtchannel2", "mycc", [b"x"])
    env = create_signed_tx(
        bundle,
        client,
        [endorse_proposal(bundle, peer, serialize_tx_rwset(rwset))],
    )
    blocks = []
    chain = SoloChain(
        "pvtchannel2",
        signer=peer,
        batch_config=BatchConfig(max_message_count=1),
        deliver=blocks.append,
    )
    chain.order(env)
    flags = peer_channel.store_block(blocks[0])
    assert flags.is_valid(0)
    missing = peer_channel.ledger.pvt_store.get_missing_pvt_data()
    assert list(missing) == [0]
    assert missing[0][0].collection == "secret"
    # hashed write still applied (the on-block part commits regardless)
    assert (
        peer_channel.ledger.state_db.get_hashed_state("mycc", "secret", kh)
        is not None
    )


def test_commit_survives_crash_between_pvt_and_block(tmp_path):
    """Regression: pvtdata store commit precedes the block append; a crash
    in between must not brick the channel on redelivery."""
    ledger = KVLedger(str(tmp_path), "ch")
    rwset = pvt_rwset_for("k1", b"v1")
    block = make_block_with_pvt(0, b"", rwset)
    pvt = {(0, "mycc", "secret"): kvrwset_bytes([("k1", b"v1")])}
    # simulate the crash: pvt store committed, block append never happened
    from fabric_tpu.ledger.pvtdatastore import PvtEntry

    ledger.pvt_store.commit(
        0, [PvtEntry(0, "mycc", "secret", kvrwset_bytes([("k1", b"v1")]))]
    )
    assert ledger.height == 0
    # redelivery completes the interrupted commit instead of raising
    flags = ledger.commit(block, rwsets=[rwset], pvt_data=pvt)
    assert flags.is_valid(0)
    assert ledger.height == 1
    assert ledger.get_private_data("mycc", "secret", "k1") == b"v1"


def test_commit_hash_not_mutated_by_failed_pvt_commit(tmp_path):
    """Regression: a hash-mismatch raise must happen before the
    commit-hash chain advances, so a retry produces the same hash."""
    ledger = KVLedger(str(tmp_path), "ch")
    rwset = pvt_rwset_for("k1", b"real")
    block = make_block_with_pvt(0, b"", rwset)
    before = ledger.commit_hash
    with pytest.raises(ValueError):
        ledger.commit(
            block,
            rwsets=[rwset],
            pvt_data={(0, "mycc", "secret"): kvrwset_bytes([("k1", b"forged")])},
        )
    assert ledger.commit_hash == before
    assert ledger.height == 0
    # retry with good data commits cleanly
    block2 = make_block_with_pvt(0, b"", rwset)
    flags = ledger.commit(
        block2,
        rwsets=[rwset],
        pvt_data={(0, "mycc", "secret"): kvrwset_bytes([("k1", b"real")])},
    )
    assert flags.is_valid(0)


def test_missing_markers_skip_invalid_txs(tmp_path):
    """Regression: missing-pvt markers computed pre-MVCC must not persist
    for txs that ended up invalid."""
    from fabric_tpu.ledger.pvtdatastore import MissingEntry

    ledger = KVLedger(str(tmp_path), "ch")
    rwset = pvt_rwset_for("k1", b"v")
    block = make_block_with_pvt(0, b"", rwset)
    # mark the tx invalid in the incoming filter (as if sig-check failed)
    flags = ValidationFlags(1, TxValidationCode.ENDORSEMENT_POLICY_FAILURE)
    block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] = flags.tobytes()
    ledger.commit(
        block,
        rwsets=[rwset],
        missing_pvt=[MissingEntry(0, "mycc", "secret")],
    )
    assert ledger.pvt_store.get_missing_pvt_data() == {}


@requires_crypto
def test_channel_treats_forged_fetched_pvt_as_missing(tmp_path, orgs):
    """Regression: hash-mismatched data from the (untrusted) fetcher must
    become a missing marker, not a commit failure."""
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
    from fabric_tpu.msp.identity import MSPManager
    from fabric_tpu.msp.signer import SigningIdentity
    from fabric_tpu.orderer import SoloChain
    from fabric_tpu.orderer.blockcutter import BatchConfig
    from fabric_tpu.peer import Channel
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.validation.validator import (
        ChaincodeDefinition,
        ChaincodeRegistry,
    )

    org1, _ = orgs
    mgr = MSPManager([org1.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("mycc", from_dsl("OR('Org1MSP.member')"))]
    )
    peer_channel = Channel(
        "pvtchannel3",
        str(tmp_path / "peer"),
        mgr,
        registry,
        PROVIDER,
        fetch_pvt=lambda blk, tx, txid, ns, coll: kvrwset_bytes(
            [("k", b"FORGED")]
        ),
        is_eligible=lambda ns, coll: True,
    )
    client = SigningIdentity(org1.users[0], PROVIDER)
    peer = SigningIdentity(org1.peers[0], PROVIDER)
    kh = hashlib.sha256(b"k").digest()
    rwset = rw.TxRwSet(
        (
            rw.NsRwSet(
                "mycc",
                coll_hashed=(
                    rw.CollHashedRwSet(
                        "secret",
                        hashed_writes=(
                            rw.KVWriteHash(
                                kh, False, hashlib.sha256(b"real").digest()
                            ),
                        ),
                    ),
                ),
            ),
        )
    )
    bundle = create_proposal(client, "pvtchannel3", "mycc", [b"x"])
    env = create_signed_tx(
        bundle,
        client,
        [endorse_proposal(bundle, peer, serialize_tx_rwset(rwset))],
    )
    blocks = []
    chain = SoloChain(
        "pvtchannel3",
        signer=peer,
        batch_config=BatchConfig(max_message_count=1),
        deliver=blocks.append,
    )
    chain.order(env)
    flags = peer_channel.store_block(blocks[0])  # must not raise
    assert flags.is_valid(0)
    missing = peer_channel.ledger.pvt_store.get_missing_pvt_data()
    assert list(missing) == [0]
    assert (
        peer_channel.ledger.get_private_data("mycc", "secret", "k") is None
    )


def test_simulator_reads_committed_pvt_data(tmp_path):
    ledger = KVLedger(str(tmp_path), "ch")
    rwset = pvt_rwset_for("k1", b"visible")
    block = make_block_with_pvt(0, b"", rwset)
    ledger.commit(
        block,
        rwsets=[rwset],
        pvt_data={(0, "mycc", "secret"): kvrwset_bytes([("k1", b"visible")])},
    )
    sim = TxSimulator(
        ledger.state_db,
        tx_id="t",
        pvt_reader=lambda ns, coll, key: ledger.get_private_data(ns, coll, key),
    )
    assert sim.get_private_data("mycc", "secret", "k1") == b"visible"
    res = sim.get_tx_simulation_results()
    hr = res.rwset.ns_rw_sets[0].coll_hashed[0].hashed_reads[0]
    assert hr.version == rw.Version(0, 0)


# ---------------- reconciler write-back (reconcile.go analog) -------------


def test_commit_reconciled_pvt(tmp_path, monkeypatch):
    """Late-arriving pvt data: complete+valid payloads are accepted,
    subsets/forgeries/garbage dropped, and newer state never regresses."""
    from fabric_tpu.ledger.kvledger import KVLedger as KL

    ledger = KVLedger(str(tmp_path), "ch")
    rwset0 = pvt_rwset_for("k1", b"secret-value")
    block0 = make_block_with_pvt(0, b"", rwset0)
    # committed WITHOUT the pvt data: missing marker recorded
    from fabric_tpu.ledger.pvtdatastore import MissingEntry

    ledger.commit(
        block0,
        rwsets=[rwset0],
        missing_pvt=[MissingEntry(0, "mycc", "secret")],
    )
    assert ledger.pvt_store.get_missing_pvt_data() == {
        0: [MissingEntry(0, "mycc", "secret")]
    }
    # the reconciler re-parses blocks; placeholder envelopes don't parse,
    # so patch the extraction to the rwsets used at commit
    monkeypatch.setattr(KL, "_extract_rwsets", lambda self, b: [rwset0])

    # 1. garbage payload: dropped, marker stays
    assert ledger.commit_reconciled_pvt(
        [(0, 0, "mycc", "secret", b"\xff\xfenot-proto")]
    ) == 0
    # 2. forged value: hash mismatch, dropped
    assert ledger.commit_reconciled_pvt(
        [(0, 0, "mycc", "secret", kvrwset_bytes([("k1", b"forged")]))]
    ) == 0
    # 3. empty subset: completeness check rejects it
    assert ledger.commit_reconciled_pvt(
        [(0, 0, "mycc", "secret", kvrwset_bytes([]))]
    ) == 0
    assert ledger.pvt_store.get_missing_pvt_data()  # marker still there

    # 4. the real thing: accepted, marker cleared, state patched
    good = kvrwset_bytes([("k1", b"secret-value")])
    assert ledger.commit_reconciled_pvt([(0, 0, "mycc", "secret", good)]) == 1
    assert ledger.pvt_store.get_missing_pvt_data() == {}
    assert ledger.get_private_data("mycc", "secret", "k1") == b"secret-value"

    # 5. staleness: a block-1 write supersedes; replaying block 0's data
    #    must not regress the state
    rwset1 = pvt_rwset_for("k1", b"newer-value")
    block1 = make_block_with_pvt(
        1, protoutil.block_header_hash(block0.header), rwset1
    )
    monkeypatch.setattr(
        KL,
        "_extract_rwsets",
        lambda self, b: [rwset0] if b.header.number == 0 else [rwset1],
    )
    ledger.commit(
        block1,
        rwsets=[rwset1],
        pvt_data={(0, "mycc", "secret"): kvrwset_bytes([("k1", b"newer-value")])},
    )
    assert ledger.get_private_data("mycc", "secret", "k1") == b"newer-value"
    assert ledger.commit_reconciled_pvt([(0, 0, "mycc", "secret", good)]) == 1
    # pvt store has the old-block record now, but state kept the new value
    assert ledger.get_private_data("mycc", "secret", "k1") == b"newer-value"
