"""Differential tests: the OpenSSL-backed fast EC path vs the pure-Python
oracle.

fastec is the default host execution path for every provider (reference
analog: bccsp/sw/ecdsa.go:41-57 riding Go's P-256 assembly); p256 stays the
clarity-first oracle.  These tests pin the two to identical semantics,
including the error-lane behaviors the reference's (bool, error) split
mandates.
"""

import hashlib

import pytest

pytest.importorskip(
    "cryptography", reason="fastec tier needs the cryptography package"
)

from fabric_tpu.crypto import der, fastec, p256  # noqa: E402
from fabric_tpu.crypto.bccsp import (
    PurePythonProvider,
    SoftwareProvider,
    VerifyError,
    ec_backend,
)


def _digest(i: int) -> bytes:
    return hashlib.sha256(b"fastec differential %d" % i).digest()


def test_backend_is_fastec():
    # cryptography is baked into this environment; a silent fallback to the
    # oracle would be a ~2000x perf regression masquerading as green tests.
    assert ec_backend() is fastec


def test_sign_verify_roundtrip_vs_oracle():
    kp = fastec.generate_keypair()
    for i in range(4):
        d = _digest(i)
        r, s = fastec.sign_digest(kp.priv, d)
        assert p256.is_low_s(s)
        assert fastec.verify_digest(kp.pub, d, r, s)
        assert p256.verify_digest(kp.pub, d, r, s)
        # wrong digest fails on both
        assert not fastec.verify_digest(kp.pub, _digest(i + 100), r, s)
        assert not p256.verify_digest(kp.pub, _digest(i + 100), r, s)


def test_oracle_signatures_verify_under_fastec():
    kp = p256.generate_keypair()
    d = _digest(7)
    r, s = p256.sign_digest(kp.priv, d, k=0x1234567DEADBEEF)
    assert fastec.verify_digest(kp.pub, d, r, s)
    assert p256.verify_digest(kp.pub, d, r, s)


def test_high_s_accepted_at_raw_layer_by_both():
    # The low-S rule lives in parse_and_precheck, NOT in verify_digest
    # (Go crypto/ecdsa accepts both nonce images).
    kp = fastec.generate_keypair()
    d = _digest(8)
    r, s = fastec.sign_digest(kp.priv, d)
    assert fastec.verify_digest(kp.pub, d, r, p256.N - s)
    assert p256.verify_digest(kp.pub, d, r, p256.N - s)


def test_out_of_range_and_off_curve_match_oracle():
    kp = fastec.generate_keypair()
    d = _digest(9)
    for r, s in [(0, 1), (1, 0), (p256.N, 1), (1, p256.N), (-1, 1)]:
        assert fastec.verify_digest(kp.pub, d, r, s) is False
        assert p256.verify_digest(kp.pub, d, r, s) is False
    off_curve = (5, 7)
    assert fastec.verify_digest(off_curve, d, 3, 3) is False
    assert p256.verify_digest(off_curve, d, 3, 3) is False


def test_non_sha256_digest_falls_back_to_oracle_semantics():
    # hashToInt truncation: leftmost 32 bytes of a longer digest.
    kp = fastec.generate_keypair()
    long_digest = hashlib.sha512(b"long").digest()
    r, s = fastec.sign_digest(kp.priv, long_digest)
    assert fastec.verify_digest(kp.pub, long_digest, r, s)
    assert p256.verify_digest(kp.pub, long_digest, r, s)


def test_pub_cache_eviction_keeps_answers_right(monkeypatch):
    monkeypatch.setattr(fastec, "_CACHE_CAP", 2)
    monkeypatch.setattr(fastec, "_PUB_CACHE", {})
    kps = [fastec.generate_keypair() for _ in range(5)]
    d = _digest(10)
    sigs = [fastec.sign_digest(kp.priv, d) for kp in kps]
    for _ in range(2):  # second pass re-materializes evicted keys
        for kp, (r, s) in zip(kps, sigs):
            assert fastec.verify_digest(kp.pub, d, r, s)


class TestProviderDifferential:
    """SoftwareProvider (OpenSSL) vs PurePythonProvider (oracle): identical
    verdicts AND identical error lanes through the full BCCSP contract."""

    def test_verdicts_and_error_lanes_agree(self):
        fast, oracle = SoftwareProvider(), PurePythonProvider()
        key = fast.key_gen()
        d = fast.hash(b"provider differential")
        sig = fast.sign(key, d)
        r, s = der.unmarshal_signature(sig)
        cases = [
            sig,  # valid
            der.marshal_signature(r, p256.N - s),  # high-S -> VerifyError
            b"\x30\x02\x02\x00",  # malformed DER -> VerifyError
            der.marshal_signature(r, (s + 1) % p256.N),  # clean False
        ]
        for c in cases:
            outcomes = []
            for prov in (fast, oracle):
                try:
                    outcomes.append(prov.verify(key.public, c, d))
                except VerifyError:
                    outcomes.append("error")
            assert outcomes[0] == outcomes[1], c.hex()
        assert fast.batch_verify(
            [key.public] * 4, cases, [d] * 4
        ) == oracle.batch_verify([key.public] * 4, cases, [d] * 4) == [
            True,
            False,
            False,
            False,
        ]

    def test_oracle_sign_verifies_under_fast_provider(self):
        oracle = PurePythonProvider()
        fast = SoftwareProvider()
        key = oracle.key_gen()
        d = oracle.hash(b"cross sign")
        sig = oracle.sign(key, d)
        assert fast.verify(key.public, sig, d)
