"""fabtrace unit tests: a firing fixture + negative control per rule
(with the PR-18 sweep's real bugs re-created in fixture form: the
pre-fix mvcc capacity-growth loop shape fires ``transfer-in-loop`` and
a jit site fed a ``len()``-shaped array fires ``recompile-hazard`` —
the shipped bucket-ladder shapes are the negative controls), the
behavior-pinned fablint jit-impure migration fixtures, loud
hotpath.toml parse errors (exit 2 from the CLI), suppression
semantics, CLI plumbing, the toolkit analyzer-registry protocol, and
the repo self-check (the CI gate invariant: ``fabtrace fabric_tpu/``
reports 0 unsuppressed findings).

Fixture code lives in *strings* on purpose: only genuine AST shapes
may feed the rules, and the fixtures deliberately sync, recompile and
leak tracers in ways package code must never exhibit.  The analyzer
itself must run without jax/numpy/cryptography — pinned here by a
subprocess whose import machinery poisons those modules."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from fabric_tpu.tools import fabreg, fabtrace, toolkit
from fabric_tpu.tools.fabtrace import (
    HotpathSpec,
    StageSpec,
    parse_hotpath,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
PKG = "fabric_tpu/m.py"

#: one fixture table exercising every section: the fixture module is a
#: device-tier module with a non-boundary stage (submit), a boundary
#: stage (settle), a bucket projection, ladder constants and a shaper
SPEC = HotpathSpec(
    stages=(
        StageSpec("m.py", "submit", boundary=False),
        StageSpec("m.py", "settle", boundary=True),
    ),
    devices=("m.py",),
    transfers=("int_to_limbs", "np.asarray", "device_put"),
    buckets=("_bucket",),
    ladders=("NLIMBS", "_BUCKETS"),
    shapers=(("pad_limbs", 1),),
)


def trace(*parts, path=PKG, rules=None, spec=SPEC):
    # each part is dedented on its own: a preamble constant and a
    # per-test body are written at different literal indents
    src = "\n".join(textwrap.dedent(p) for p in parts)
    findings, _n = fabtrace.analyze_source(src, path, rules, hotpath=spec)
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# recompile-hazard: shape provenance at jit call sites
# ---------------------------------------------------------------------------

JIT_PREAMBLE = """
    import jax
    import jax.numpy as jnp

    def kernel(x):
        return x * 2

    kernel_jit = jax.jit(kernel)
"""


def test_recompile_fires_on_len_shaped_argument():
    findings = trace(
        JIT_PREAMBLE,
        """
        def run(vals):
            n = len(vals)
            x = jnp.zeros((n, 20))
            return kernel_jit(x)
        """,
        rules=["recompile-hazard"],
    )
    assert rule_ids(findings) == ["recompile-hazard"]
    assert "kernel_jit" in findings[0].message


def test_recompile_negative_control_is_the_bucket_ladder():
    findings = trace(
        JIT_PREAMBLE,
        """
        def run(vals):
            n = _bucket(len(vals))
            x = jnp.zeros((n, NLIMBS))
            return kernel_jit(x)
        """,
        rules=["recompile-hazard"],
    )
    assert findings == []


def test_recompile_shaper_projection_launders_the_size():
    # pad_limbs(x, n) returns an array whose shape is its arg-1 size:
    # a bucketed n stays static through it, a raw len() stays data
    body = """
        def run(vals):
            x = pad_limbs(vals, {size})
            return kernel_jit(x)
        """
    assert rule_ids(
        trace(
            JIT_PREAMBLE, body.format(size="len(vals)"),
            rules=["recompile-hazard"],
        )
    ) == ["recompile-hazard"]
    assert trace(
        JIT_PREAMBLE, body.format(size="_bucket(len(vals))"),
        rules=["recompile-hazard"],
    ) == []


def test_recompile_unknown_shapes_stay_silent():
    # only PROVABLY data-dependent shapes fire: an opaque argument must
    # not be guessed at (that was fablint-era noise)
    findings = trace(
        JIT_PREAMBLE,
        """
        def run(x):
            return kernel_jit(x)
        """,
        rules=["recompile-hazard"],
    )
    assert findings == []


def test_recompile_rebinding_through_the_ladder_clears_the_taint():
    # reshape to a ladder constant after a data-shaped intermediate
    findings = trace(
        JIT_PREAMBLE,
        """
        def run(vals):
            x = jnp.zeros((len(vals), 20))
            x = x.reshape(NLIMBS, 20)
            return kernel_jit(x)
        """,
        rules=["recompile-hazard"],
    )
    assert findings == []


def test_recompile_stage_function_reports_once_with_all_rules():
    # stage functions are walked twice (general pass + sync pass); the
    # hazard must be reported exactly once
    findings = trace(
        JIT_PREAMBLE,
        """
        def submit(vals):
            x = jnp.zeros((len(vals), 20))
            return kernel_jit(x)
        """
    )
    assert rule_ids(findings) == ["recompile-hazard"]


# ---------------------------------------------------------------------------
# static-arg-churn
# ---------------------------------------------------------------------------

STATIC_PREAMBLE = """
    import jax
    import jax.numpy as jnp

    def kernel(x, n):
        return x[:n]

    kernel_jit = jax.jit(kernel, static_argnames=("n",))
"""


def test_churn_fires_on_per_call_varying_static_kwarg():
    findings = trace(
        STATIC_PREAMBLE,
        """
        def run(vals, x):
            return kernel_jit(x, n=len(vals))
        """,
        rules=["static-arg-churn"],
    )
    assert rule_ids(findings) == ["static-arg-churn"]
    assert "'n'" in findings[0].message


def test_churn_fires_on_positional_static_argnums():
    findings = trace(
        """
        import jax

        def kernel(x, n):
            return x[:n]

        kernel_jit = jax.jit(kernel, static_argnums=(1,))

        def run(vals, x):
            return kernel_jit(x, len(vals))
        """,
        rules=["static-arg-churn"],
    )
    assert rule_ids(findings) == ["static-arg-churn"]


def test_churn_negative_control_is_the_bucketed_static():
    findings = trace(
        STATIC_PREAMBLE,
        """
        def run(vals, x):
            return kernel_jit(x, n=_bucket(len(vals)))
        """,
        rules=["static-arg-churn"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# host-sync-hot-path: the declarative stage table
# ---------------------------------------------------------------------------


def test_sync_float_of_device_value_in_stage_fires():
    findings = trace(
        JIT_PREAMBLE,
        """
        def submit(x):
            y = kernel_jit(x)
            return float(y)
        """,
        rules=["host-sync-hot-path"],
    )
    assert rule_ids(findings) == ["host-sync-hot-path"]
    assert "'submit'" in findings[0].message


def test_sync_block_until_ready_in_stage_fires_unconditionally():
    findings = trace(
        JIT_PREAMBLE,
        """
        def submit(x):
            kernel_jit(x).block_until_ready()
        """,
        rules=["host-sync-hot-path"],
    )
    assert rule_ids(findings) == ["host-sync-hot-path"]


def test_sync_np_asarray_of_device_value_in_stage_fires():
    findings = trace(
        JIT_PREAMBLE,
        """
        import numpy as np

        def submit(x):
            y = kernel_jit(x)
            return np.asarray(y)
        """,
        rules=["host-sync-hot-path"],
    )
    assert rule_ids(findings) == ["host-sync-hot-path"]


def test_sync_boundary_stage_is_legal():
    # the same sync in the declared boundary stage (settle) is the
    # pipeline's join point — no finding
    findings = trace(
        JIT_PREAMBLE,
        """
        def settle(x):
            y = kernel_jit(x)
            return float(y)
        """,
        rules=["host-sync-hot-path"],
    )
    assert findings == []


def test_sync_host_value_conversion_is_clean():
    # float() of a host ndarray is not a device sync
    findings = trace(
        """
        import numpy as np

        def submit(x):
            y = np.zeros((3,))
            return float(y[0] if False else y)
        """,
        rules=["host-sync-hot-path"],
    )
    assert findings == []


def test_sync_undeclared_function_is_out_of_scope():
    # only declared stage rows are judged: a helper in the same module
    # may sync freely
    findings = trace(
        JIT_PREAMBLE,
        """
        def helper(x):
            return float(kernel_jit(x))
        """,
        rules=["host-sync-hot-path"],
    )
    assert findings == []


def test_sync_nested_closure_runs_at_another_time():
    # a closure dispatched from a stage drains at the boundary — its
    # body must not be charged to the stage
    findings = trace(
        JIT_PREAMBLE,
        """
        def submit(x):
            y = kernel_jit(x)

            def check():
                return float(y)
            return check
        """,
        rules=["host-sync-hot-path"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# transfer-in-loop: the vectorized-ingest worklist
# ---------------------------------------------------------------------------


def test_transfer_fires_inside_per_lane_loop():
    findings = trace(
        """
        def pack(keys):
            out = []
            for k in keys:
                out.append(int_to_limbs(k))
            return out
        """,
        rules=["transfer-in-loop"],
    )
    assert rule_ids(findings) == ["transfer-in-loop"]
    assert "int_to_limbs" in findings[0].message
    assert "'pack'" in findings[0].message


def test_transfer_fires_inside_comprehension_body():
    findings = trace(
        """
        def pack(keys):
            return [int_to_limbs(k) for k in keys]
        """,
        rules=["transfer-in-loop"],
    )
    assert rule_ids(findings) == ["transfer-in-loop"]


def test_transfer_for_iter_is_evaluated_once():
    # np.asarray in the For's iterable runs once, not per lane — the
    # multichannel fix's target shape is the negative control
    findings = trace(
        """
        import numpy as np

        def drain(dev):
            total = 0
            for row in np.asarray(dev):
                total += 1
            return total
        """,
        rules=["transfer-in-loop"],
    )
    assert findings == []


def test_transfer_straight_line_conversion_is_clean():
    findings = trace(
        """
        import numpy as np

        def pack(keys):
            cols = np.asarray(keys)
            return int_to_limbs(cols)
        """,
        rules=["transfer-in-loop"],
    )
    assert findings == []


def test_transfer_one_level_interprocedural_via_local_helper():
    # a loop over a local helper that performs the conversion is still
    # a per-lane conversion (the tpu_provider _key_limbs shape)
    findings = trace(
        """
        def _encode(k):
            return int_to_limbs(k)

        def pack(keys):
            return [_encode(k) for k in keys]
        """,
        rules=["transfer-in-loop"],
    )
    assert rule_ids(findings) == ["transfer-in-loop"]
    assert "_encode" in findings[0].message


def test_transfer_foreign_method_sharing_a_leaf_is_not_resolved():
    # regression for the multichannel false positive: w.convert(...) is
    # some other object's method — sharing a bare leaf with a local
    # bearing helper must not fire
    findings = trace(
        """
        def convert(k):
            return int_to_limbs(k)

        def run(workers, keys):
            out = []
            for w in workers:
                out.append(w.convert(keys))
            return out
        """,
        rules=["transfer-in-loop"],
    )
    assert findings == []


def test_transfer_non_device_module_is_out_of_scope():
    findings = trace(
        """
        def pack(keys):
            return [int_to_limbs(k) for k in keys]
        """,
        path="fabric_tpu/other.py",
        rules=["transfer-in-loop"],
    )
    assert findings == []


def test_transfer_mvcc_growth_loop_shape_fires():
    # the PR-18 sweep's real bug: per-doubling jnp.concatenate inside
    # the capacity-growth while loop (fixed to a single extend)
    spec = HotpathSpec(
        devices=("m.py",),
        transfers=("jnp.concatenate", "jnp.full"),
    )
    findings = trace(
        """
        import jax.numpy as jnp

        def grow(self, n):
            while n > self._cap:
                self._cap *= 2
                self._dev = jnp.concatenate(
                    [self._dev, jnp.full((self._cap, 2), -1)]
                )
        """,
        rules=["transfer-in-loop"],
        spec=spec,
    )
    assert rule_ids(findings) == ["transfer-in-loop"] * 2


# ---------------------------------------------------------------------------
# tracer-leak
# ---------------------------------------------------------------------------


def test_leak_append_of_traced_value_to_module_list_fires():
    findings = trace(
        """
        import jax

        _cache = []

        @jax.jit
        def kernel(x):
            y = x * 2
            _cache.append(y)
            return y
        """,
        rules=["tracer-leak"],
    )
    assert rule_ids(findings) == ["tracer-leak"]
    assert "enclosing-scope container" in findings[0].message


def test_leak_instance_state_store_fires():
    findings = trace(
        """
        import jax

        @jax.jit
        def kernel(self, x):
            y = x + 1
            self._last = y
            return y
        """,
        rules=["tracer-leak"],
    )
    assert rule_ids(findings) == ["tracer-leak"]
    assert "instance/module state" in findings[0].message


def test_leak_global_rebinding_fires():
    findings = trace(
        """
        import jax

        _last = None

        @jax.jit
        def kernel(x):
            global _last
            _last = x * 2
            return x
        """,
        rules=["tracer-leak"],
    )
    assert rule_ids(findings) == ["tracer-leak"]


def test_leak_untainted_append_is_clean():
    # bookkeeping of non-traced values is not a tracer leak
    findings = trace(
        """
        import jax

        _log = []

        @jax.jit
        def kernel(x):
            _log.append("called")
            return x * 2
        """,
        rules=["tracer-leak"],
    )
    assert findings == []


def test_leak_local_container_is_clean():
    findings = trace(
        """
        import jax

        @jax.jit
        def kernel(x):
            acc = []
            for i in range(3):
                acc.append(x * i)
            return acc
        """,
        rules=["tracer-leak"],
    )
    assert findings == []


def test_leak_untraced_function_is_out_of_scope():
    findings = trace(
        """
        _cache = []

        def plain(x):
            y = x * 2
            _cache.append(y)
            return y
        """,
        rules=["tracer-leak"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# jit-impure: the fablint migration, behavior-pinned + dataflow promotion
# ---------------------------------------------------------------------------
# The first four fixtures are the fablint PR-3 fixtures verbatim — the
# rule moved tools in PR 18 and its verdicts must not move with it.


def test_impure_print_in_decorated_jit_fires():
    findings = trace(
        """
        import jax

        @jax.jit
        def kernel(x):
            print(x)
            return x * 2
        """,
        rules=["jit-impure"],
    )
    assert rule_ids(findings) == ["jit-impure"]
    assert "print" in findings[0].message


def test_impure_host_calls_in_wrapped_jit_fire():
    findings = trace(
        """
        import time

        import jax
        import numpy as np

        def kernel(x):
            t = time.time()
            y = np.asarray(x)
            y.block_until_ready()
            return y

        kernel_jit = jax.jit(kernel)
        """,
        rules=["jit-impure"],
    )
    assert len(findings) >= 2
    assert set(rule_ids(findings)) == {"jit-impure"}


def test_impure_pure_static_partial_jit_is_clean():
    findings = trace(
        """
        from functools import partial

        import jax

        @partial(jax.jit, static_argnames=("n",))
        def kernel(x, n):
            return x[:n]
        """,
        rules=["jit-impure"],
    )
    assert findings == []


def test_impure_unjitted_host_wrapper_is_clean():
    findings = trace(
        """
        import numpy as np

        def to_host(x):
            return np.asarray(x)
        """,
        rules=["jit-impure"],
    )
    assert findings == []


def test_impure_os_environ_read_fires():
    # the dataflow promotion fablint could not see: env reads pin the
    # trace-time value into the compiled artifact
    findings = trace(
        """
        import os

        import jax

        @jax.jit
        def kernel(x):
            if os.environ["FABRIC_DEBUG"]:
                return x
            return x * 2
        """,
        rules=["jit-impure"],
    )
    assert rule_ids(findings) == ["jit-impure"]
    assert "os.environ" in findings[0].message


def test_impure_os_getenv_fires():
    findings = trace(
        """
        import os

        import jax

        @jax.jit
        def kernel(x):
            mode = os.getenv("FABRIC_MODE")
            return x if mode else x * 2
        """,
        rules=["jit-impure"],
    )
    assert rule_ids(findings) == ["jit-impure"]


def test_impure_mutated_module_state_read_fires():
    findings = trace(
        """
        import jax

        _MODES = {}

        def setup(name):
            _MODES[name] = 1

        @jax.jit
        def kernel(x):
            return x * len(_MODES)
        """,
        rules=["jit-impure"],
    )
    assert rule_ids(findings) == ["jit-impure"]
    assert "_MODES" in findings[0].message


def test_impure_immutable_module_constant_is_clean():
    findings = trace(
        """
        import jax

        _LIMBS = 20

        @jax.jit
        def kernel(x):
            return x * _LIMBS
        """,
        rules=["jit-impure"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# hotpath.toml: packaged table + loud parse errors
# ---------------------------------------------------------------------------


def test_packaged_hotpath_table_parses_and_names_the_plane():
    spec = fabtrace.load_default_hotpath()
    stage_fns = {s.function for s in spec.stages}
    assert "CommitPipeline.submit" in stage_fns
    assert "VerifyBatcher._settle" in stage_fns
    boundary = {s.function for s in spec.stages if s.boundary}
    # the declared join points: the batcher settle and the validator
    # entry points that hand results back to the host
    assert "VerifyBatcher._settle" in boundary
    assert any(m.endswith("crypto/tpu_provider.py") for m in spec.devices)
    assert any(m.endswith("ledger/mvcc_device.py") for m in spec.devices)
    assert "int_to_limbs" in spec.transfers
    assert "_bucket" in spec.buckets
    assert "NLIMBS" in spec.ladders
    assert dict(spec.shapers)["pad_limbs"] == 1
    # the tower-bounded kernels are deliberately NOT device-tier rows
    assert not any(m.endswith("ops/fp12.py") for m in spec.devices)
    assert not any(m.endswith("ops/bignum.py") for m in spec.devices)


@pytest.mark.parametrize(
    "text,err",
    [
        ("[[bogus]]\n", "unknown section"),
        ("[sideways]\n", "unknown section"),
        ("[[stage]]\nmodule = \"m.py\"\n", "missing required key"),
        ("module = \"m.py\"\n", "outside a"),
        ("[[stage]]\nmodule = \"m.py\"\nfunction = \"f\"\ncolor = \"red\"\n",
         "unknown key"),
        ("[[stage]]\nmodule - \"m.py\"\n", "expected 'key = value'"),
        ("[[stage]]\nmodule = maybe\n", "expected"),
        ("[[stage]]\nmodule = \"m.txt\"\nfunction = \"f\"\n",
         "must be a .py path"),
        ("[[stage]]\nmodule = \"m.py\"\nfunction = \"f\"\nboundary = 3\n",
         "must be a bool"),
        ("[[shaper]]\nfunction = \"pad\"\narg = -1\n", "arg must be >= 0"),
        ("[[shaper]]\nfunction = \"pad\"\narg = \"one\"\n", "must be a int"),
        ("[[bucket]]\nfunction = \"\"\n", "non-empty"),
    ],
)
def test_hotpath_table_parse_errors_are_loud(text, err):
    with pytest.raises(ValueError, match=err):
        parse_hotpath(text, "<bad>")


def test_cli_rejects_bad_hotpath_table(tmp_path, capsys):
    bad = tmp_path / "hotpath.toml"
    bad.write_text("[[bogus]]\n")
    target = tmp_path / "fabric_tpu" / "m.py"
    target.parent.mkdir()
    target.write_text("x = 1\n")
    rc = fabtrace.main(["--hotpath", str(bad), str(target)])
    assert rc == 2
    assert "hotpath table" in capsys.readouterr().err


def test_cli_rejects_missing_hotpath_table(tmp_path, capsys):
    target = tmp_path / "fabric_tpu" / "m.py"
    target.parent.mkdir()
    target.write_text("x = 1\n")
    rc = fabtrace.main(
        ["--hotpath", str(tmp_path / "nope.toml"), str(target)]
    )
    assert rc == 2
    assert "hotpath table" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# suppressions, CLI, syntax errors
# ---------------------------------------------------------------------------


def test_suppression_absorbs_finding_and_is_counted():
    src = textwrap.dedent(
        """
        import jax

        @jax.jit
        def kernel(x):
            print(x)  # fabtrace: disable=jit-impure  # fixture traces the print
            return x * 2
        """
    )
    findings, n = fabtrace.analyze_source(
        src, PKG, ["jit-impure"], hotpath=SPEC
    )
    assert findings == []
    assert n == 1


def test_suppression_for_another_rule_does_not_absorb():
    src = textwrap.dedent(
        """
        import jax

        @jax.jit
        def kernel(x):
            print(x)  # fabtrace: disable=tracer-leak  # wrong rule
            return x * 2
        """
    )
    findings, n = fabtrace.analyze_source(
        src, PKG, ["jit-impure"], hotpath=SPEC
    )
    assert rule_ids(findings) == ["jit-impure"]
    assert n == 0


def test_suppression_disable_all_silences_the_line():
    src = textwrap.dedent(
        """
        def pack(keys):
            return [int_to_limbs(k) for k in keys]  # fabtrace: disable=all  # fixture
        """
    )
    findings, n = fabtrace.analyze_source(src, PKG, hotpath=SPEC)
    assert findings == []
    assert n == 1


def test_cli_json_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "fabric_tpu" / "m.py"
    bad.parent.mkdir()
    bad.write_text(
        "import jax\n\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    print(x)\n"
        "    return x * 2\n"
    )
    rc = fabtrace.main(["--json", str(bad)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    assert [f["rule"] for f in out["findings"]] == ["jit-impure"]

    clean = tmp_path / "fabric_tpu" / "ok.py"
    clean.write_text("x = 1\n")
    assert fabtrace.main([str(clean)]) == 0
    capsys.readouterr()

    assert fabtrace.main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rid in fabtrace.RULES:
        assert rid in listed

    assert fabtrace.main(["--rules", "no-such-rule", str(clean)]) == 2
    assert fabtrace.main([str(tmp_path / "missing.py")]) == 2
    assert fabtrace.main([]) == 2


def test_syntax_error_is_reported_not_raised():
    findings = trace("def broken(:\n", rules=["jit-impure"])
    assert rule_ids(findings) == ["syntax-error"]


def test_analyzer_never_imports_the_analyzed_stack(tmp_path):
    # the gate runs in minimal CI images: fabtrace must sweep the whole
    # package with jax/jaxlib/numpy/cryptography UNIMPORTABLE.  A None
    # entry in sys.modules makes any import of the name raise.
    code = textwrap.dedent(
        """
        import sys

        for name in ("jax", "jaxlib", "numpy", "cryptography"):
            sys.modules[name] = None
        from fabric_tpu.tools import fabtrace

        rc = fabtrace.main(["fabric_tpu/"])
        sys.exit(rc)
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


# ---------------------------------------------------------------------------
# toolkit registry + fabreg staleness protocol
# ---------------------------------------------------------------------------


def test_fabtrace_is_registered_with_the_toolkit():
    assert "fabtrace" in toolkit.ANALYZER_TOOLS
    spec = toolkit.analyzer_spec("fabtrace")
    assert spec is not None
    assert spec.module == "fabric_tpu.tools.fabtrace"
    # package-scoped: tests craft syncing/recompiling fixtures by design
    assert spec.pkg_scope_only is True


def test_live_suppression_keys_reports_absorbing_comments():
    src = textwrap.dedent(
        """
        import jax

        @jax.jit
        def kernel(x):
            print(x)  # fabtrace: disable=jit-impure  # trace-time print fixture
            return x * 2
        """
    )
    keys = fabtrace.live_suppression_keys({PKG: src}, {"jit-impure"})
    assert len(keys) == 1
    ((path, line, rule),) = keys
    assert rule == "jit-impure"
    assert path.endswith("fabric_tpu/m.py")
    assert line == 6


def test_fabreg_suppression_stale_judges_fabtrace_via_the_registry():
    live = textwrap.dedent(
        """
        import jax

        @jax.jit
        def kernel(x):
            print(x)  # fabtrace: disable=jit-impure  # trace-time print fixture
            return x * 2
        """
    )
    stale = textwrap.dedent(
        """
        def quiet():
            x = 1  # fabtrace: disable=recompile-hazard  # outlived its cause
            return x
        """
    )
    findings, _stats = fabreg.analyze_sources(
        {"fabric_tpu/live.py": live, "fabric_tpu/stale.py": stale},
        rule_ids=["suppression-stale"],
    )
    assert rule_ids(findings) == ["suppression-stale"]
    assert findings[0].path == "fabric_tpu/stale.py"
    assert "fabtrace" in findings[0].message


# ---------------------------------------------------------------------------
# repo self-check: the CI gate invariant
# ---------------------------------------------------------------------------


def test_repo_has_zero_unsuppressed_findings():
    findings, stats = fabtrace.analyze_paths([str(REPO_ROOT / "fabric_tpu")])
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in findings
    )
    assert stats["files"] > 100
    # the triaged by-design suppressions (NOTES_BUILD PR 18 ledger):
    # the generator-table/schedule precomputes, the tower-bounded Fp12
    # coefficient walks, the chunk-granular drain join point, and the
    # two vectorized-ingest worklist rows (pairing mont, MSM pack)
    assert stats["suppressed"] == 18
