"""North-star-shaped scale test: a 1,000-tx block with real envelopes
and signatures through the full channel commit pipeline (parse ->
validate -> MVCC -> sqlite commit), the in-suite version of BASELINE
config #2 (bench.py measures the same shape on the accelerator)."""

import pytest

from conftest import requires_crypto
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.peer.channel import Channel
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import protoutil
from fabric_tpu.validation.txflags import TxValidationCode
from fabric_tpu.validation.validator import (
    ChaincodeDefinition,
    ChaincodeRegistry,
)

PROVIDER = SoftwareProvider()
CHANNEL = "scalechan"
N_TXS = 1000


@requires_crypto
@pytest.mark.slow
def test_thousand_tx_block_commits(tmp_path):
    org1 = generate_org("org1.example.com", "Org1MSP")
    org2 = generate_org("org2.example.com", "Org2MSP")
    mgr = MSPManager(
        [org1.msp(provider=PROVIDER), org2.msp(provider=PROVIDER)]
    )
    registry = ChaincodeRegistry(
        [
            ChaincodeDefinition(
                "cc", from_dsl("AND('Org1MSP.member','Org2MSP.member')")
            )
        ]
    )
    client = SigningIdentity(org1.users[0], PROVIDER)
    endorsers = [
        SigningIdentity(org1.peers[0], PROVIDER),
        SigningIdentity(org2.peers[0], PROVIDER),
    ]

    block = protoutil.new_block(0, b"")
    for i in range(N_TXS):
        key = f"k{i:04d}"
        # one MVCC conflict pair per 100 txs: tx writes a key an earlier
        # in-block tx wrote and reads stale state
        if i % 100 == 99:
            key = f"k{i - 1:04d}"
        results = serialize_tx_rwset(
            rw.TxRwSet(
                (
                    rw.NsRwSet(
                        "cc",
                        (rw.KVRead(key, None),),
                        (rw.KVWrite(key, False, b"v"),),
                    ),
                )
            )
        )
        bundle = create_proposal(client, CHANNEL, "cc", [b"put", key.encode()])
        responses = [endorse_proposal(bundle, e, results) for e in endorsers]
        block.data.data.append(
            create_signed_tx(bundle, client, responses).SerializeToString()
        )
    protoutil.seal_block(block)

    ch = Channel(CHANNEL, str(tmp_path), mgr, registry, PROVIDER)
    flags = ch.store_block(block)

    codes = [TxValidationCode(int(c)) for c in flags.asarray()]
    n_conflicts = sum(
        1 for c in codes if c == TxValidationCode.MVCC_READ_CONFLICT
    )
    n_valid = sum(1 for c in codes if c == TxValidationCode.VALID)
    assert n_conflicts == N_TXS // 100
    assert n_valid == N_TXS - n_conflicts
    assert ch.ledger.height == 1
    assert ch.ledger.get_state("cc", "k0500") == b"v"
    # restart: savepoint recovery, no replay, same state
    ch.ledger.block_store.close()
    ch.ledger.pvt_store.close()
    ch.ledger.state_db.close()
    from fabric_tpu.ledger.kvledger import KVLedger

    again = KVLedger(str(tmp_path), CHANNEL)
    assert again.height == 1
    # tx 999 targeted k0998 (and was the MVCC-invalid one), so k0999
    # itself was never written
    assert again.get_state("cc", "k0999") is None
    assert again.get_state("cc", "k0998") == b"v"
