"""Offline peer admin commands (reference usable-inter-nal/peer/node
pause/resume/rollback/reset/rebuild-dbs + kvledger pause_resume.go)."""

import os

import pytest

from conftest import requires_crypto
import yaml

from fabric_tpu.cli import peer as peer_cli
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.protos import protoutil


def write_rwset(ns, items):
    return rw.TxRwSet(
        (
            rw.NsRwSet(
                ns,
                (),
                tuple(rw.KVWrite(k, v is None, v or b"") for k, v in items),
            ),
        )
    )


_IDENTITY = None


def _identity():
    global _IDENTITY
    if _IDENTITY is None:
        from fabric_tpu.msp.cryptogen import generate_org
        from fabric_tpu.msp.signer import SigningIdentity

        org = generate_org("org1.nodeadmin", "Org1MSP")
        _IDENTITY = SigningIdentity(org.users[0])
    return _IDENTITY


def make_block(channel_id, number, prev_hash, rwsets):
    """Real parseable envelopes: rebuild-dbs replays by re-extracting
    rwsets from the stored blocks, so dummy payloads won't do."""
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset

    signer = _identity()
    block = protoutil.new_block(number, prev_hash)
    for txrw in rwsets:
        bundle = create_proposal(signer, channel_id, "cc", [b"put"])
        resp = endorse_proposal(bundle, signer, serialize_tx_rwset(txrw))
        env = create_signed_tx(bundle, signer, [resp])
        block.data.data.append(env.SerializeToString())
    return protoutil.seal_block(block)


def build_chain(fs_path, channel_id, n_blocks=3):
    ledger = KVLedger(os.path.join(fs_path, channel_id), channel_id)
    prev = b"\x00" * 32
    for n in range(n_blocks):
        rwsets = [write_rwset("cc", [(f"k{n}", b"v%d" % n)])]
        block = make_block(channel_id, n, prev, rwsets)
        ledger.commit(block, rwsets=rwsets)
        prev = protoutil.block_header_hash(block.header)
    ledger.close()


def config_file(tmp_path, fs_path):
    path = tmp_path / "core.yaml"
    path.write_text(yaml.safe_dump({"peer": {"fileSystemPath": fs_path}}))
    return str(path)


def run(argv):
    return peer_cli.main(argv)


@requires_crypto
def test_pause_resume_marker_and_join_refusal(tmp_path):
    fs = str(tmp_path / "peer-data")
    build_chain(fs, "ch1")
    cfg = config_file(tmp_path, fs)

    assert run(["node", "pause", "--config", cfg, "-c", "ch1"]) == 0
    marker = os.path.join(fs, "ch1", "PAUSED")
    assert os.path.exists(marker)

    # a paused channel refuses to load (kvledger pause_resume.go)
    from fabric_tpu.msp.cryptogen import generate_org
    from fabric_tpu.msp.identity import MSPManager
    from fabric_tpu.msp.signer import SigningIdentity
    from fabric_tpu.nodes.peer import PeerNode
    from fabric_tpu.channelconfig import (
        ApplicationProfile,
        OrdererProfile,
        OrganizationProfile,
        Profile,
        genesis_block,
    )
    from fabric_tpu.validation.validator import ChaincodeRegistry

    org = generate_org("org1.admin", "Org1MSP")
    oorg = generate_org("orderer.admin", "OrdererMSP")
    gblock = genesis_block(
        Profile(
            application=ApplicationProfile(
                organizations=[
                    OrganizationProfile("Org1MSP", org.msp_config())
                ]
            ),
            orderer=OrdererProfile(
                orderer_type="solo",
                organizations=[
                    OrganizationProfile("OrdererMSP", oorg.msp_config())
                ],
            ),
        ),
        "ch1",
    )
    node = PeerNode(
        fs,
        MSPManager([org.msp()]),
        SigningIdentity(org.peers[0]),
        lambda cid: ChaincodeRegistry([]),
    )
    with pytest.raises(ValueError, match="paused"):
        node.join_channel(gblock)

    assert run(["node", "resume", "--config", cfg, "-c", "ch1"]) == 0
    assert not os.path.exists(marker)


@requires_crypto
def test_rollback_truncates_and_replays(tmp_path):
    fs = str(tmp_path / "peer-data")
    build_chain(fs, "ch2", n_blocks=4)
    cfg = config_file(tmp_path, fs)

    assert run(
        ["node", "rollback", "--config", cfg, "-c", "ch2", "-b", "1"]
    ) == 0
    ledger = KVLedger(os.path.join(fs, "ch2"), "ch2")
    assert ledger.height == 2
    assert ledger.get_state("cc", "k1") == b"v1"
    assert ledger.get_state("cc", "k3") is None
    ledger.close()


@requires_crypto
def test_reset_rolls_every_channel_to_genesis(tmp_path):
    fs = str(tmp_path / "peer-data")
    build_chain(fs, "cha", n_blocks=3)
    build_chain(fs, "chb", n_blocks=2)
    cfg = config_file(tmp_path, fs)

    assert run(["node", "reset", "--config", cfg]) == 0
    for ch in ("cha", "chb"):
        ledger = KVLedger(os.path.join(fs, ch), ch)
        assert ledger.height == 1
        ledger.close()


@requires_crypto
def test_rebuild_dbs_rebuilds_state(tmp_path):
    fs = str(tmp_path / "peer-data")
    build_chain(fs, "ch3", n_blocks=3)
    cfg = config_file(tmp_path, fs)

    # vandalize the derived state db, then rebuild from the block store
    state_path = os.path.join(fs, "ch3", "ch3.state.db")
    assert os.path.exists(state_path)
    os.remove(state_path)
    assert run(["node", "rebuild-dbs", "--config", cfg, "-c", "ch3"]) == 0
    ledger = KVLedger(os.path.join(fs, "ch3"), "ch3")
    assert ledger.get_state("cc", "k2") == b"v2"
    ledger.close()


def test_version_commands():
    """reference `peer version` / `osnadmin`-era `orderer version`."""
    import io
    from contextlib import redirect_stdout

    import fabric_tpu
    from fabric_tpu.cli.orderer import main as orderer_main
    from fabric_tpu.cli.peer import main as peer_main

    from fabric_tpu.cli.configtxlator import main as lator_main
    from fabric_tpu.cli.cryptogen import main as cryptogen_main
    from fabric_tpu.cli.idemixgen import main as idemixgen_main

    for main_fn, binary in (
        (peer_main, "peer"),
        (orderer_main, "orderer"),
        (lator_main, "configtxlator"),
        (cryptogen_main, "cryptogen"),
        (idemixgen_main, "idemixgen"),
    ):
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = main_fn(["version"])
        out = buf.getvalue()
        assert rc == 0
        assert out.startswith(f"{binary}:")
        assert fabric_tpu.__version__ in out

    from fabric_tpu.cli.configtxgen import main as configtxgen_main

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = configtxgen_main(["--version"])
    assert rc == 0 and fabric_tpu.__version__ in buf.getvalue()
