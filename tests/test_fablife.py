"""fablife unit tests: a firing fixture + negative control per rule
(with the two HISTORICAL bugs re-created in fixture form: the
pre-PR-10 sidecar stop()/accept() shape fires ``thread-unjoined`` and
the pre-PR-8 unclamped ``retry_after_ms`` sleep fires
``wire-unclamped`` — the fixed shapes are the negative controls),
suppression semantics, loud pairs.toml parse errors, CLI plumbing, the
toolkit analyzer-registry protocol, and the repo self-check (the CI
gate invariant: ``fablife fabric_tpu/ tests/ bench.py`` reports 0
unsuppressed findings).

Fixture code lives in *strings* on purpose: the repo self-check scans
this file too, and only genuine AST shapes may feed the rules."""

import json
import textwrap
from pathlib import Path

import pytest

from fabric_tpu.tools import fablife, fabreg, toolkit
from fabric_tpu.tools.fablife import PairSpec, parse_pairs

REPO_ROOT = Path(__file__).resolve().parent.parent
PKG = "fabric_tpu/m.py"
SERVE = "fabric_tpu/serve/m.py"


def analyze(src, path=PKG, rules=None, pairs=()):
    findings, _n = fablife.analyze_source(
        textwrap.dedent(src), path, rules, pairs=pairs
    )
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# thread-unjoined
# ---------------------------------------------------------------------------

# the pre-PR-10 sidecar shape: stop() flips a flag but never joins (or
# wakes) the accept thread — every teardown ate the full join timeout
SIDECAR_PRE_PR10 = """
    import threading

    class Sidecar:
        def start(self):
            self._accept = threading.Thread(
                target=self._accept_loop, name="serve-accept", daemon=True
            )
            self._accept.start()

        def stop(self):
            self._stopping = True
"""

# the post-PR-10 shape: shutdown the listener, then join
SIDECAR_FIXED = """
    import socket
    import threading

    class Sidecar:
        def start(self):
            self._accept = threading.Thread(
                target=self._accept_loop, name="serve-accept", daemon=True
            )
            self._accept.start()

        def stop(self):
            self._stopping = True
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._accept.join(timeout=2.0)
"""


def test_thread_unjoined_fires_on_pre_pr10_sidecar_shape():
    findings = analyze(SIDECAR_PRE_PR10, rules=["thread-unjoined"])
    assert rule_ids(findings) == ["thread-unjoined"]
    assert "_accept" in findings[0].message


def test_thread_unjoined_negative_control_is_the_fixed_sidecar():
    assert analyze(SIDECAR_FIXED, rules=["thread-unjoined"]) == []


def test_thread_list_join_loop_satisfies_and_its_absence_fires():
    clean = """
        import threading

        class S:
            def start(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()
                self._threads.append(t)

            def stop(self):
                for t in list(self._threads):
                    t.join(timeout=2.0)
    """
    assert analyze(clean, rules=["thread-unjoined"]) == []
    leaky = """
        import threading

        class S:
            def start(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()
                self._threads.append(t)

            def stop(self):
                self._stopping = True
    """
    findings = analyze(leaky, rules=["thread-unjoined"])
    assert rule_ids(findings) == ["thread-unjoined"]
    assert "_threads" in findings[0].message


def test_thread_unjoined_unbound_start_always_fires():
    src = """
        import threading

        def spawn():
            threading.Thread(target=work, daemon=True).start()
    """
    findings = analyze(src, rules=["thread-unjoined"])
    assert rule_ids(findings) == ["thread-unjoined"]
    assert "unbound" in findings[0].message


def test_thread_unjoined_ownership_transfer_satisfies():
    # handed to a registrar / joined through an alias / stored on
    # another owner object — all ownership transfers, not leaks
    src = """
        import threading

        def spawn(reg, session):
            a = threading.Thread(target=work)
            a.start()
            reg.register(a)
            b = threading.Thread(target=work)
            b.start()
            t = b
            t.join(timeout=1.0)
            c = threading.Thread(target=work)
            session._thread = c
            c.start()
            d = threading.Thread(target=work)
            d.start()
            return d
    """
    assert analyze(src, rules=["thread-unjoined"]) == []


def test_thread_unjoined_scoped_to_the_package():
    assert (
        analyze(SIDECAR_PRE_PR10, path="tests/helper.py",
                rules=["thread-unjoined"])
        == []
    )


# ---------------------------------------------------------------------------
# fd-leak
# ---------------------------------------------------------------------------


def test_fd_leak_straight_line_rmtree_fires_finally_satisfies():
    leaky = """
        import shutil
        import tempfile

        def run():
            d = tempfile.mkdtemp(prefix="x")
            do_work(d)
            shutil.rmtree(d)
    """
    findings = analyze(leaky, rules=["fd-leak"])
    assert rule_ids(findings) == ["fd-leak"]
    assert "straight-line" in findings[0].message
    clean = """
        import shutil
        import tempfile

        def run():
            d = tempfile.mkdtemp(prefix="x")
            try:
                do_work(d)
            finally:
                shutil.rmtree(d, ignore_errors=True)
    """
    assert analyze(clean, rules=["fd-leak"]) == []


def test_fd_leak_tempdir_path_derivation_tracks_through_os_path_join():
    # the fabchaos serve-socket shape: the tracked var is DERIVED from
    # the mkdtemp return; rmtree(dirname(addr)) in a finally releases
    clean = """
        import os
        import shutil
        import tempfile

        def run():
            addr = os.path.join(tempfile.mkdtemp(prefix="s"), "s.sock")
            try:
                serve(addr)
            finally:
                shutil.rmtree(os.path.dirname(addr), ignore_errors=True)
    """
    assert analyze(clean, rules=["fd-leak"]) == []
    # ...and passing the path to a call is NOT an ownership transfer
    leaky = """
        import os
        import tempfile

        def run():
            addr = os.path.join(tempfile.mkdtemp(prefix="s"), "s.sock")
            serve(addr)
    """
    assert rule_ids(analyze(leaky, rules=["fd-leak"])) == ["fd-leak"]


def test_fd_leak_dropped_tempdir_path_fires():
    src = """
        import tempfile

        def run():
            serve(tempfile.mkdtemp(prefix="x"))
    """
    findings = analyze(src, rules=["fd-leak"])
    assert rule_ids(findings) == ["fd-leak"]
    assert "dropped" in findings[0].message


def test_fd_leak_fixture_teardown_after_yield_satisfies():
    src = """
        import shutil
        import tempfile

        def tmp_fixture():
            d = tempfile.mkdtemp(prefix="t")
            yield d
            shutil.rmtree(d, ignore_errors=True)
    """
    assert analyze(src, rules=["fd-leak"]) == []


def test_fd_leak_registered_cleanup_satisfies():
    src = """
        import atexit
        import shutil
        import tempfile

        def run():
            d = tempfile.mkdtemp(prefix="x")
            atexit.register(shutil.rmtree, d, ignore_errors=True)
            do_work(d)
    """
    assert analyze(src, rules=["fd-leak"]) == []


def test_fd_leak_tempdir_facet_covers_tests_and_bench():
    src = """
        import tempfile

        def helper():
            d = tempfile.mkdtemp(prefix="x")
            do_work(d)
    """
    assert rule_ids(
        analyze(src, path="tests/helper.py", rules=["fd-leak"])
    ) == ["fd-leak"]


def test_fd_leak_socket_with_and_finally_satisfy_bare_fires():
    leaky = """
        import socket

        def dial(addr):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.connect(addr)
            s.close()
    """
    findings = analyze(leaky, rules=["fd-leak"])
    assert rule_ids(findings) == ["fd-leak"]
    clean = """
        import socket

        def dial(addr):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.connect(addr)
            finally:
                s.close()

        def dial2(addr):
            with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
                s.connect(addr)
    """
    assert analyze(clean, rules=["fd-leak"]) == []
    # fd facets pin the package only: a test-process socket dies with it
    assert analyze(leaky, path="tests/helper.py", rules=["fd-leak"]) == []


def test_fd_leak_attr_stored_socket_needs_class_release():
    clean = """
        import socket

        class Server:
            def start(self):
                self._listener = socket.socket()

            def stop(self):
                self._listener.close()
    """
    assert analyze(clean, rules=["fd-leak"]) == []
    leaky = """
        import socket

        class Server:
            def start(self):
                self._listener = socket.socket()
    """
    findings = analyze(leaky, rules=["fd-leak"])
    assert rule_ids(findings) == ["fd-leak"]
    assert "_listener" in findings[0].message


def test_fd_leak_return_hands_ownership_to_the_caller():
    src = """
        import socket
        import tempfile

        def make_sock():
            s = socket.socket()
            return s

        def make_dir():
            d = tempfile.mkdtemp()
            return d
    """
    assert analyze(src, rules=["fd-leak"]) == []


# ---------------------------------------------------------------------------
# lock-leak
# ---------------------------------------------------------------------------


def test_lock_leak_bare_acquire_fires_finally_release_satisfies():
    leaky = """
        class C:
            def f(self):
                self._lock.acquire()
                work()
                self._lock.release()
    """
    findings = analyze(leaky, rules=["lock-leak"])
    assert rule_ids(findings) == ["lock-leak"]
    assert "with" in findings[0].message
    clean = """
        class C:
            def f(self):
                self._lock.acquire()
                try:
                    work()
                finally:
                    self._lock.release()

            def g(self):
                with self._lock:
                    work()
    """
    assert analyze(clean, rules=["lock-leak"]) == []


# ---------------------------------------------------------------------------
# pair-imbalance
# ---------------------------------------------------------------------------

QOS_PAIR = PairSpec(
    name="qos-lane", acquire="try_acquire", release=("release",),
    base_like=("ledger", "qos"), mode="base", conditional=True,
    doc="lane ledger",
)
BATCHER_PAIR = PairSpec(
    name="batcher-admit", acquire="try_submit", release=(),
    base_like=("batcher",), mode="result", conditional=True,
    doc="admission resolver",
)
GATE_PAIR = PairSpec(
    name="cooldown-verdict", acquire="ready",
    release=("record_failure", "record_success"),
    base_like=("gate",), mode="base", conditional=True, doc="gate",
)


def test_pair_imbalance_success_path_missing_release_fires():
    src = """
        def f(ledger):
            if ledger.try_acquire(1, 4):
                if overloaded():
                    return None
                work()
                ledger.release(1, 4)
    """
    findings = analyze(src, rules=["pair-imbalance"], pairs=[QOS_PAIR])
    assert rule_ids(findings) == ["pair-imbalance"]
    assert "qos-lane" in findings[0].message


def test_pair_imbalance_release_on_every_success_path_satisfies():
    src = """
        def f(ledger):
            if ledger.try_acquire(1, 4):
                if overloaded():
                    ledger.release(1, 4)
                    return None
                work()
                ledger.release(1, 4)

        def g(ledger):
            if not ledger.try_acquire(1, 4):
                return None
            try:
                work()
            finally:
                ledger.release(1, 4)
    """
    assert analyze(src, rules=["pair-imbalance"], pairs=[QOS_PAIR]) == []


def test_pair_imbalance_base_like_filters_other_receivers():
    src = """
        def f(executor):
            if executor.try_acquire(1):
                return work()
    """
    assert analyze(src, rules=["pair-imbalance"], pairs=[QOS_PAIR]) == []


def test_pair_imbalance_split_phase_class_release_is_the_weak_tier():
    # the serve sidecar shape: lanes release on dispatcher pickup, in
    # ANOTHER method of the owning class (the on_dispatch hook)
    src = """
        class Server:
            def handle(self):
                if self.qos.try_acquire(1, 4):
                    self.enqueue()

            def on_dispatch(self):
                self.qos.release(1, 4)
    """
    assert analyze(src, rules=["pair-imbalance"], pairs=[QOS_PAIR]) == []


def test_pair_imbalance_result_mode_dropped_resolver_fires():
    src = """
        def f(batcher, x):
            batcher.try_submit(x)
    """
    findings = analyze(src, rules=["pair-imbalance"], pairs=[BATCHER_PAIR])
    assert rule_ids(findings) == ["pair-imbalance"]
    assert "drops its result" in findings[0].message


def test_pair_imbalance_result_mode_called_or_handed_satisfies():
    src = """
        def f(batcher, x):
            r = batcher.try_submit(x)
            if r is None:
                return None
            return r()

        def g(batcher, x):
            return batcher.try_submit(x)

        def h(batcher, x, sink):
            r = batcher.try_submit(x)
            if r is not None:
                sink.push(r)
    """
    assert analyze(src, rules=["pair-imbalance"], pairs=[BATCHER_PAIR]) == []


def test_pair_imbalance_result_mode_closure_capture_satisfies():
    # the hostec pool shape: futures are resolved by the returned
    # closure — the closure is the new owner
    spec = PairSpec(
        name="pool-submit", acquire="submit",
        release=("resolve", "shutdown_pool"), base_like=("pool",),
        mode="result", conditional=False, doc="pool shard",
    )
    src = """
        def f(pool, shards):
            futures = [pool.submit(run, s) for s in shards]

            def resolve():
                out = []
                for fu in futures:
                    out.extend(fu.result())
                return out

            return resolve
    """
    assert analyze(src, rules=["pair-imbalance"], pairs=[spec]) == []
    # ...and a declared teardown leaf discharges the failure edge
    src2 = """
        def f(pool, shards):
            futures = [pool.submit(run, s) for s in shards]
            try:
                return [fu.result() for fu in futures]
            except Exception:
                shutdown_pool(broken=True)
                return None
    """
    assert analyze(src2, rules=["pair-imbalance"], pairs=[spec]) == []


def test_pair_imbalance_cooldown_verdict_fires_and_records_satisfy():
    leaky = """
        def f(gate):
            if gate.ready():
                rebuild()
    """
    findings = analyze(leaky, rules=["pair-imbalance"], pairs=[GATE_PAIR])
    assert rule_ids(findings) == ["pair-imbalance"]
    clean = """
        def f(gate):
            if gate.ready():
                try:
                    rebuild()
                    gate.record_success()
                except Exception:
                    gate.record_failure()
    """
    assert analyze(clean, rules=["pair-imbalance"], pairs=[GATE_PAIR]) == []


def test_pair_imbalance_module_global_base_released_elsewhere_in_file():
    # the hostec _POOL_GATE shape: the gate is module-owned; ready() in
    # one function, the verdict recorded by the rebuild/teardown helpers
    src = """
        _GATE = make_gate()

        def get_pool():
            if _GATE.ready():
                return build()
            return None

        def teardown(broken):
            if broken:
                _GATE.record_failure()
            else:
                _GATE.record_success()
    """
    spec = PairSpec(
        name="cooldown-verdict", acquire="ready",
        release=("record_failure", "record_success"),
        base_like=("gate",), mode="base", conditional=True, doc="gate",
    )
    assert analyze(src, rules=["pair-imbalance"], pairs=[spec]) == []


# ---------------------------------------------------------------------------
# pairs.toml
# ---------------------------------------------------------------------------


def test_pairs_toml_packaged_table_parses_and_names_the_contracts():
    specs = fablife.load_default_pairs()
    by_name = {s.name: s for s in specs}
    assert {"qos-lane", "pool-submit", "batcher-admit",
            "cooldown-verdict"} <= set(by_name)
    assert by_name["qos-lane"].release == ("release",)
    assert by_name["qos-lane"].conditional
    assert by_name["pool-submit"].mode == "result"


@pytest.mark.parametrize(
    "text,err",
    [
        ('[[pair]]\nname = "x"\nacquire = "a"\nmode = "base"\n',
         "missing required key"),
        ('[[pair]]\nname = "x"\nacquire = "a"\nrelease = ["r"]\n'
         'mode = "sideways"\n', "mode must be"),
        ('[[pair]]\nname = "x"\nacquire = "a"\nrelease = []\n'
         'mode = "base"\n', "at least one release"),
        ('name = "orphan"\n', "outside a \\[\\[pair\\]\\]"),
        ('[pairs]\n', "unknown section"),
        ('[[pair]]\nname = "x"\nacquire = "a"\nrelease = [r]\n'
         'mode = "base"\n', "quoted"),
        ('[[pair]]\nname = "x"\nacquire = "a"\nrelease = ["r"]\n'
         'mode = "base"\n[[pair]]\nname = "x"\nacquire = "b"\n'
         'release = ["r"]\nmode = "base"\n', "duplicate pair name"),
    ],
)
def test_pairs_toml_parse_errors_are_loud(text, err):
    with pytest.raises(ValueError, match=err):
        parse_pairs(text)


def test_cli_rejects_bad_pair_table(tmp_path, capsys):
    bad = tmp_path / "pairs.toml"
    bad.write_text('[[pair]]\nmode = "sideways"\n')
    target = tmp_path / "m.py"
    target.write_text("x = 1\n")
    rc = fablife.main(["--pairs", str(bad), str(target)])
    assert rc == 2
    assert "pair table" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# wire-unclamped
# ---------------------------------------------------------------------------

# the pre-PR-8 shape: a u32 off the wire slept verbatim — a
# server-controlled unbounded client stall
RETRY_PRE_PR8 = """
    import time

    def wait_for_capacity(sock):
        status, retry_ms, mask, msg = decode_reply(sock)
        time.sleep(retry_ms / 1000.0)
"""

# the post-PR-8 shape: clamp to the client's own policy cap first
RETRY_FIXED = """
    import time

    def wait_for_capacity(sock, cap_s):
        status, retry_ms, mask, msg = decode_reply(sock)
        hint_s = min(retry_ms / 1000.0, cap_s)
        time.sleep(hint_s)
"""


def test_wire_unclamped_fires_on_pre_pr8_retry_after_ms_sleep():
    findings = analyze(RETRY_PRE_PR8, rules=["wire-unclamped"])
    assert rule_ids(findings) == ["wire-unclamped"]
    assert "retry_after_ms" in findings[0].message


def test_wire_unclamped_negative_control_is_the_clamped_shape():
    assert analyze(RETRY_FIXED, rules=["wire-unclamped"]) == []


def test_wire_unclamped_covers_reader_ints_into_sinks():
    src = """
        import collections
        import struct

        def handle(r, sock, buf):
            n = r.u32()
            q = collections.deque(maxlen=n)
            b = bytearray(r.u16())
            (count,) = struct.unpack(">I", buf)
            sock.settimeout(1.0)
            poll(timeout=count)
    """
    findings = analyze(src, rules=["wire-unclamped"])
    assert rule_ids(findings) == ["wire-unclamped"] * 3
    assert {"maxlen=" in f.message or "bytearray" in f.message
            or "timeout=" in f.message for f in findings} == {True}


def test_wire_unclamped_reassignment_and_min_untaint():
    src = """
        def handle(r):
            n = r.u32()
            n = min(n, 64)
            wait(n)
            m = r.u32()
            m = 8
            wait(m)
    """
    assert analyze(src, rules=["wire-unclamped"]) == []


def test_wire_unclamped_sequence_repeat_allocation_fires():
    src = """
        def handle(r):
            n = r.u32()
            pad = b"\\x00" * n
            return pad
    """
    findings = analyze(src, rules=["wire-unclamped"])
    assert rule_ids(findings) == ["wire-unclamped"]
    assert "sequence-repeat" in findings[0].message


# ---------------------------------------------------------------------------
# blocking-unbudgeted
# ---------------------------------------------------------------------------


def test_blocking_unbudgeted_fires_on_request_path_waits():
    src = """
        def pump(q, ev, t):
            item = q.get()
            ev.wait()
            t.join()
    """
    findings = analyze(src, path=SERVE, rules=["blocking-unbudgeted"])
    assert rule_ids(findings) == ["blocking-unbudgeted"] * 3


def test_blocking_unbudgeted_budgeted_and_non_queue_shapes_pass():
    src = """
        def pump(q, ev, t, d, parts):
            item = q.get(timeout=0.5)
            ev.wait(0.5)
            t.join(timeout=2.0)
            x = d.get("key")
            s = ", ".join(parts)
    """
    assert analyze(src, path=SERVE, rules=["blocking-unbudgeted"]) == []


def test_blocking_unbudgeted_block_true_without_timeout_fires():
    src = """
        def pump(q):
            return q.get(True)
    """
    findings = analyze(src, path=SERVE, rules=["blocking-unbudgeted"])
    assert rule_ids(findings) == ["blocking-unbudgeted"]


def test_blocking_unbudgeted_recv_needs_a_bounding_call():
    leaky = """
        def read(sock):
            return sock.recv(4096)
    """
    assert rule_ids(
        analyze(leaky, path=SERVE, rules=["blocking-unbudgeted"])
    ) == ["blocking-unbudgeted"]
    clean = """
        def read(sock, budget):
            sock.settimeout(budget)
            return sock.recv(4096)
    """
    assert analyze(clean, path=SERVE, rules=["blocking-unbudgeted"]) == []


def test_blocking_unbudgeted_scoped_to_request_paths():
    src = """
        def pump(q):
            return q.get()
    """
    assert analyze(
        src, path="fabric_tpu/ledger/m.py", rules=["blocking-unbudgeted"]
    ) == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_suppression_absorbs_finding_and_is_counted():
    src = """
        import threading

        def spawn():
            threading.Thread(target=work).start()  # fablife: disable=thread-unjoined  # bounded helper: exits with work()
    """
    findings, n_supp = fablife.analyze_source(
        textwrap.dedent(src), PKG, ["thread-unjoined"], pairs=()
    )
    assert findings == []
    assert n_supp == 1


def test_suppression_disable_all_silences_the_line():
    src = """
        import threading

        def spawn():
            threading.Thread(target=work).start()  # fablife: disable=all  # fixture
    """
    findings, n_supp = fablife.analyze_source(
        textwrap.dedent(src), PKG, ["thread-unjoined"], pairs=()
    )
    assert findings == []
    assert n_supp == 1


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_json_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "fabric_tpu" / "m.py"
    bad.parent.mkdir()
    bad.write_text(
        "import threading\n\n"
        "def spawn():\n"
        "    threading.Thread(target=w).start()\n"
    )
    rc = fablife.main(["--json", str(bad)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    assert [f["rule"] for f in out["findings"]] == ["thread-unjoined"]

    clean = tmp_path / "fabric_tpu" / "ok.py"
    clean.write_text("x = 1\n")
    assert fablife.main([str(clean)]) == 0
    capsys.readouterr()

    assert fablife.main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rid in fablife.RULES:
        assert rid in listed

    assert fablife.main(["--rules", "no-such-rule", str(clean)]) == 2
    assert fablife.main([str(tmp_path / "missing.py")]) == 2
    assert fablife.main([]) == 2


def test_syntax_error_is_reported_not_raised():
    findings = analyze("def broken(:\n", rules=["fd-leak"])
    assert rule_ids(findings) == ["syntax-error"]


# ---------------------------------------------------------------------------
# toolkit registry + fabreg staleness protocol
# ---------------------------------------------------------------------------


def test_fablife_is_registered_with_the_toolkit():
    assert "fablife" in toolkit.ANALYZER_TOOLS
    spec = toolkit.analyzer_spec("fablife")
    assert spec is not None
    assert spec.module == "fabric_tpu.tools.fablife"
    assert spec.pkg_scope_only is False  # its gate scans tests/ too


def test_live_suppression_keys_reports_absorbing_comments():
    src = textwrap.dedent(
        """
        import threading

        def spawn():
            threading.Thread(target=w).start()  # fablife: disable=thread-unjoined  # bounded helper
        """
    )
    keys = fablife.live_suppression_keys({PKG: src}, {"thread-unjoined"})
    assert len(keys) == 1
    ((path, line, rule),) = keys
    assert rule == "thread-unjoined"
    assert path.endswith("fabric_tpu/m.py")


def test_fabreg_suppression_stale_judges_fablife_via_the_registry():
    live = textwrap.dedent(
        """
        import threading

        def spawn():
            threading.Thread(target=w).start()  # fablife: disable=thread-unjoined  # bounded helper
        """
    )
    stale = textwrap.dedent(
        """
        def quiet():
            x = 1  # fablife: disable=fd-leak  # outlived its cause
            return x
        """
    )
    findings, _stats = fabreg.analyze_sources(
        {"fabric_tpu/live.py": live, "fabric_tpu/stale.py": stale},
        rule_ids=["suppression-stale"],
    )
    assert rule_ids(findings) == ["suppression-stale"]
    assert findings[0].path == "fabric_tpu/stale.py"
    assert "fablife" in findings[0].message


# ---------------------------------------------------------------------------
# repo self-check: the CI gate invariant
# ---------------------------------------------------------------------------


def test_repo_has_zero_unsuppressed_findings():
    findings, stats = fablife.analyze_paths(
        [
            str(REPO_ROOT / "fabric_tpu"),
            str(REPO_ROOT / "tests"),
            str(REPO_ROOT / "bench.py"),
        ]
    )
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in findings
    )
    # the triaged by-design suppressions (NOTES_BUILD PR 15) are live
    assert stats["suppressed"] >= 1
