"""VerifyBatcher (SURVEY P7): cross-channel coalescing into bucketed
device launches with bounded-queue backpressure."""

import threading
import time


from conftest import requires_crypto

from fabric_tpu.parallel.batcher import VerifyBatcher


class FakeProvider:
    """Verdict = (key == b"ok"); records launch sizes."""

    def __init__(self, gate=None):
        self.launch_sizes = []
        self.gate = gate

    def batch_verify_async(self, keys, sigs, digests):
        if self.gate is not None:
            self.gate.wait()
        self.launch_sizes.append(len(keys))
        out = [k == b"ok" for k in keys]
        return lambda: out


def test_slicing_returns_each_requests_own_lanes():
    prov = FakeProvider()
    b = VerifyBatcher(prov, linger_s=0.001)
    try:
        r1 = b.submit([b"ok", b"bad"], [b"s"] * 2, [b"d"] * 2)
        r2 = b.submit([b"bad", b"ok", b"ok"], [b"s"] * 3, [b"d"] * 3)
        assert r1() == [True, False]
        assert r2() == [False, True, True]
        assert b.lanes == 5
    finally:
        b.stop()


def test_concurrent_submissions_coalesce():
    prov = FakeProvider()
    b = VerifyBatcher(prov, linger_s=0.02)
    results = {}
    try:

        def worker(i):
            n = 1 + (i % 4)
            keys = [b"ok" if (i + j) % 2 == 0 else b"no" for j in range(n)]
            results[i] = (
                keys,
                b.submit(keys, [b"s"] * n, [b"d"] * n)(),
            )

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(40)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        b.stop()

    for keys, out in results.values():
        assert out == [k == b"ok" for k in keys]
    assert len(results) == 40
    # 40 requests from 8+ racing threads must NOT mean 40 device launches
    assert b.launches < 40, prov.launch_sizes
    assert sum(prov.launch_sizes) == b.lanes


def test_backpressure_bounds_pending_lanes():
    gate = threading.Event()
    prov = FakeProvider(gate=gate)
    b = VerifyBatcher(prov, linger_s=0.0, max_pending_lanes=4)
    try:
        # dispatcher picks this up and stalls inside the provider; its
        # permits were released at dispatch
        first = b.submit([b"ok"], [b"s"], [b"d"])
        time.sleep(0.05)
        # these 4 hold every permit while queued behind the stalled launch
        second = b.submit([b"ok"] * 4, [b"s"] * 4, [b"d"] * 4)

        blocked = threading.Event()
        unblocked = threading.Event()

        def overflow():
            blocked.set()
            r = b.submit([b"ok"], [b"s"], [b"d"])
            unblocked.set()
            r()

        t = threading.Thread(target=overflow, daemon=True)
        t.start()
        assert blocked.wait(1.0)
        time.sleep(0.1)
        assert not unblocked.is_set()  # backpressured while device stalled
        gate.set()
        assert unblocked.wait(2.0)
        assert first() == [True]
        assert second() == [True] * 4
        t.join(timeout=2.0)
    finally:
        gate.set()
        b.stop()


def test_oversized_request_does_not_deadlock():
    prov = FakeProvider()
    b = VerifyBatcher(prov, linger_s=0.0, max_pending_lanes=4)
    try:
        out = b.submit([b"ok"] * 10, [b"s"] * 10, [b"d"] * 10)()
        assert out == [True] * 10
    finally:
        b.stop()


def test_stop_settles_outstanding_requests():
    prov = FakeProvider()
    b = VerifyBatcher(prov, linger_s=0.001)
    r = b.submit([b"ok"], [b"s"], [b"d"])
    b.stop()
    assert r() == [True]


@requires_crypto
def test_with_real_tpu_provider():
    """End-to-end through the device kernel: mixed-size concurrent
    requests, one verdict per lane, bit-exact vs expectations."""
    import hashlib

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
    )

    from fabric_tpu.crypto import der, p256
    from fabric_tpu.crypto.bccsp import ECDSAPublicKey
    from fabric_tpu.crypto.tpu_provider import TPUProvider

    sk = ec.generate_private_key(ec.SECP256R1())
    nums = sk.public_key().public_numbers()
    pub = ECDSAPublicKey(nums.x, nums.y)
    triples = []
    for i in range(6):
        msg = b"batcher %d" % i
        digest = hashlib.sha256(msg).digest()
        r, s = decode_dss_signature(sk.sign(msg, ec.ECDSA(hashes.SHA256())))
        if not p256.is_low_s(s):
            s = p256.N - s
        triples.append((pub, der.marshal_signature(r, s), digest))

    b = VerifyBatcher(TPUProvider(), linger_s=0.01)
    try:
        good = b.submit(
            [t[0] for t in triples],
            [t[1] for t in triples],
            [t[2] for t in triples],
        )
        bad_digest = hashlib.sha256(b"tampered").digest()
        bad = b.submit([pub], [triples[0][1]], [bad_digest])
        assert good() == [True] * 6
        assert bad() == [False]
    finally:
        b.stop()


def test_batching_provider_adapter():
    """BatchingProvider: batch paths route through the shared batcher,
    everything else passes through to the wrapped provider."""
    from fabric_tpu.parallel.batcher import BatchingProvider

    prov = FakeProvider()
    bp = BatchingProvider(prov, linger_s=0.001)
    try:
        assert bp.batch_verify([b"ok", b"no"], [b"s"] * 2, [b"d"] * 2) == [
            True,
            False,
        ]
        resolver = bp.batch_verify_async([b"ok"], [b"s"], [b"d"])
        assert resolver() == [True]
        # passthrough of non-batch attributes
        assert bp.launch_sizes == prov.launch_sizes
        assert bp.batcher.lanes == 3
    finally:
        bp.stop()


class SlowResolveProvider:
    """Fixed per-launch 'RTT' in the resolver (tunnel simulation)."""

    def __init__(self, rtt_s):
        self.rtt_s = rtt_s
        self.launch_sizes = []

    def batch_verify_async(self, keys, sigs, digests):
        self.launch_sizes.append(len(keys))
        out = [k == b"ok" for k in keys]

        def resolve():
            time.sleep(self.rtt_s)
            return out

        return resolve


def test_rtt_autodetect_switches_to_passthrough():
    """High per-launch RTT flips the batcher to passthrough: each small
    request becomes its own launch instead of coalescing."""
    prov = SlowResolveProvider(rtt_s=0.08)  # 80ms >> 25ms threshold
    b = VerifyBatcher(prov, linger_s=0.005)
    try:
        assert b.mode == "coalesce"  # no signal yet: default
        for _ in range(4):
            b.submit([b"ok"] * 8, [b""] * 8, [b""] * 8)()
        assert b.rtt_ema_ms is not None and b.rtt_ema_ms > 30
        assert b.mode == "passthrough"
        # in passthrough, concurrent submissions do NOT merge
        prov.launch_sizes.clear()
        rs = [b.submit([b"ok"] * 8, [b""] * 8, [b""] * 8) for _ in range(3)]
        for r in rs:
            r()
        assert all(s == 8 for s in prov.launch_sizes)
    finally:
        b.stop()


def test_rtt_autodetect_stays_coalescing_when_fast():
    prov = SlowResolveProvider(rtt_s=0.0)
    b = VerifyBatcher(prov, linger_s=0.005)
    try:
        for _ in range(6):
            b.submit([b"ok"] * 8, [b""] * 8, [b""] * 8)()
        assert b.rtt_ema_ms is not None and b.rtt_ema_ms < 20
        assert b.mode == "coalesce"
    finally:
        b.stop()


def test_forced_mode_env(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_BATCHER_MODE", "passthrough")
    prov = SlowResolveProvider(rtt_s=0.0)
    b = VerifyBatcher(prov, linger_s=0.005)
    try:
        assert b.mode == "passthrough"
    finally:
        b.stop()


class HangingResolveProvider:
    """Resolver blocks until released — a wedged device tunnel."""

    def __init__(self):
        self.release = threading.Event()

    def batch_verify_async(self, keys, sigs, digests):
        def resolve():
            self.release.wait(30)
            return [True] * len(keys)

        return resolve


def test_stop_settles_hung_resolver_fail_closed():
    """stop() must not leave resolve() callers blocked behind a hung
    resolver: after the join times out, in-flight requests settle with
    all-False verdicts (fail-closed, never a guessed True)."""
    prov = HangingResolveProvider()
    b = VerifyBatcher(prov, linger_s=0.0, join_timeout_s=0.2)
    r = b.submit([b"ok", b"ok"], [b"s"] * 2, [b"d"] * 2)
    time.sleep(0.05)  # let the dispatcher pick it up and hang
    t0 = time.monotonic()
    try:
        b.stop()
        out = r()
    finally:
        prov.release.set()
    assert out == [False, False]
    assert time.monotonic() - t0 < 5


def test_stop_is_idempotent():
    prov = FakeProvider()
    b = VerifyBatcher(prov, linger_s=0.001)
    r = b.submit([b"ok"], [b"s"], [b"d"])
    b.stop()
    b.stop()  # second stop: no deadlock, no double sentinel trouble
    assert r() == [True]


def test_stop_then_submit_raises_and_leaks_nothing():
    prov = FakeProvider()
    b = VerifyBatcher(prov, linger_s=0.001)
    b.stop()
    try:
        b.submit([b"ok"], [b"s"], [b"d"])
        raised = False
    except RuntimeError:
        raised = True
    assert raised
    assert b._lanes_free == b._max_pending_lanes  # admission released
    assert not b._inflight


class FlakyDispatchProvider:
    """First dispatch attempts raise ConnectionError, then succeed —
    exercises the bounded transient retry in the dispatcher."""

    def __init__(self, failures):
        self.failures = failures
        self.attempts = 0

    def batch_verify_async(self, keys, sigs, digests):
        self.attempts += 1
        if self.attempts <= self.failures:
            raise ConnectionError("transient flap")
        out = [k == b"ok" for k in keys]
        return lambda: out


def test_dispatch_retries_transient_then_succeeds():
    from fabric_tpu.common.retry import RetryPolicy

    prov = FlakyDispatchProvider(failures=2)
    b = VerifyBatcher(
        prov,
        linger_s=0.0,
        dispatch_retry=RetryPolicy(
            base_s=0.001, multiplier=2, cap_s=0.01, deadline_s=1,
            max_attempts=3,
        ),
    )
    try:
        assert b.submit([b"ok", b"no"], [b"s"] * 2, [b"d"] * 2)() == [
            True,
            False,
        ]
        assert prov.attempts == 3
    finally:
        b.stop()


def test_dispatch_retry_budget_exhausted_propagates():
    from fabric_tpu.common.retry import RetryPolicy

    prov = FlakyDispatchProvider(failures=100)
    b = VerifyBatcher(
        prov,
        linger_s=0.0,
        dispatch_retry=RetryPolicy(
            base_s=0.001, multiplier=2, cap_s=0.01, deadline_s=1,
            max_attempts=2,
        ),
    )
    try:
        r = b.submit([b"ok"], [b"s"], [b"d"])
        try:
            r()
            raised = False
        except ConnectionError:
            raised = True
        assert raised
        assert prov.attempts == 3  # 1 try + 2 retries
    finally:
        b.stop()


def test_injected_submit_fault_fails_caller_without_leaking_lanes():
    from fabric_tpu.common.faults import FaultPlan, InjectedFault, plan_installed

    prov = FakeProvider()
    b = VerifyBatcher(prov, linger_s=0.001, max_pending_lanes=8)
    try:
        with plan_installed(FaultPlan.parse("batcher.submit=raise:1.0")):
            try:
                b.submit([b"ok"], [b"s"], [b"d"])
                raised = False
            except InjectedFault:
                raised = True
        assert raised
        assert b._lanes_free == 8  # nothing admitted, nothing leaked
        # the batcher still works after the plan is gone
        assert b.submit([b"ok"], [b"s"], [b"d"])() == [True]
    finally:
        b.stop()


def test_stop_wakes_admission_blocked_submitter():
    """A submitter blocked on lane admission (permits held by requests
    queued behind a hung dispatcher) must be released by stop() with an
    error — not wait forever on permits that will never come back."""
    prov = HangingResolveProvider()
    b = VerifyBatcher(
        prov, linger_s=0.0, max_pending_lanes=2, join_timeout_s=0.2
    )
    # dispatched immediately (permits released at dispatch), then the
    # dispatcher wedges inside the resolver
    b.submit([b"ok", b"ok"], [b"s"] * 2, [b"d"] * 2)
    time.sleep(0.05)
    # queued behind the wedge: holds both permits
    b.submit([b"ok", b"ok"], [b"s"] * 2, [b"d"] * 2)

    outcome = []

    def blocked_submit():
        try:
            b.submit([b"ok"], [b"s"], [b"d"])
            outcome.append("admitted")
        except RuntimeError:
            outcome.append("stopped")

    t = threading.Thread(target=blocked_submit, daemon=True)
    t.start()
    time.sleep(0.1)
    assert not outcome  # genuinely blocked in admission
    try:
        b.stop()
        t.join(timeout=2.0)
    finally:
        prov.release.set()
    assert outcome == ["stopped"]


class HoldFirstThenFailProvider:
    """Launch 1 blocks until released (so launch 2 queues behind it),
    launch 2 raises a hard error — the steady-state launch-failure
    path must still drain launch 1's pending resolver."""

    def __init__(self):
        self.n = 0
        self.release = threading.Event()

    def batch_verify_async(self, keys, sigs, digests):
        self.n += 1
        if self.n == 1:
            self.release.wait(5)
            out = [k == b"ok" for k in keys]
            return lambda: out
        raise ValueError("hard provider error")


def test_launch_failure_drains_pending_resolvers():
    prov = HoldFirstThenFailProvider()
    b = VerifyBatcher(prov, linger_s=0.0)
    try:
        ra = b.submit([b"ok"], [b"s"], [b"d"])
        time.sleep(0.05)  # dispatcher takes A and blocks in its launch
        rb = b.submit([b"ok"], [b"s"], [b"d"])
        prov.release.set()  # A launches; B's launch then hard-fails
        done = []
        t = threading.Thread(target=lambda: done.append(ra()), daemon=True)
        t.start()
        t.join(timeout=3.0)
        # pre-fix: A's resolver stayed pending behind the blocking
        # q.get() and this join timed out
        assert done == [[True]]
        try:
            rb()
            raised = False
        except ValueError:
            raised = True
        assert raised
    finally:
        b.stop()
