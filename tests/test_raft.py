"""Raft consenter: election, replication, WAL recovery, leader failover,
snapshot catch-up, membership change/eviction (reference
orderer/consensus/etcdraft)."""

import struct

import pytest

from fabric_tpu.orderer.blockcutter import BatchConfig
from fabric_tpu.orderer.raft import WAL, Entry, RaftNode, SnapshotFile
from fabric_tpu.orderer.raft_chain import NotLeaderError, RaftChain
from fabric_tpu.protos import common_pb2, protoutil


def make_env(payload: bytes) -> common_pb2.Envelope:
    env = common_pb2.Envelope()
    env.payload = payload
    return env


class Cluster:
    """Deterministic in-memory raft cluster."""

    def __init__(self, tmp_path, ids=(1, 2, 3), partitioned=()):
        self.partitioned = set(partitioned)
        self.queues = {i: [] for i in ids}
        self.chains = {}
        for i in ids:
            self.chains[i] = RaftChain(
                "ch",
                i,
                ids,
                wal_dir=str(tmp_path / f"node{i}"),
                batch_config=BatchConfig(max_message_count=2),
                snapshot_interval=0,
                transport=self._make_transport(i),
            )

    def _make_transport(self, frm):
        def send(to, msg):
            if frm in self.partitioned or to in self.partitioned:
                return
            if to in self.queues:
                self.queues[to].append(msg)

        return send

    def run(self, ticks=50):
        """Advance until quiescent or ticks exhausted."""
        for _ in range(ticks):
            for i, chain in self.chains.items():
                if i in self.partitioned:
                    continue
                chain.tick()
            self.deliver()

    def deliver(self, rounds=20):
        for _ in range(rounds):
            moved = False
            for i, chain in self.chains.items():
                q, self.queues[i] = self.queues[i], []
                for m in q:
                    if i in self.partitioned:
                        continue
                    chain.step(m)
                    moved = True
            if not moved:
                return

    @property
    def leader(self):
        for i, c in self.chains.items():
            if c.node.role == "leader" and i not in self.partitioned:
                return c
        return None


def test_election_and_replication(tmp_path):
    c = Cluster(tmp_path)
    c.run(30)
    leader = c.leader
    assert leader is not None

    # two envs = one batch (max_message_count=2) -> one block everywhere
    leader.order(make_env(b"tx1"))
    leader.order(make_env(b"tx2"))
    c.run(10)
    for chain in c.chains.values():
        assert chain.height == 1, chain.node.id
    b = leader.get_block(0)
    assert len(b.data.data) == 2


def test_followers_reject_order(tmp_path):
    c = Cluster(tmp_path)
    c.run(30)
    followers = [ch for ch in c.chains.values() if ch.node.role != "leader"]
    assert followers
    with pytest.raises(NotLeaderError):
        followers[0].order(make_env(b"tx"))


def test_leader_failover_preserves_chain(tmp_path):
    c = Cluster(tmp_path)
    c.run(30)
    old_leader = c.leader
    old_leader.order(make_env(b"a"))
    old_leader.order(make_env(b"b"))
    c.run(10)
    assert all(ch.height == 1 for ch in c.chains.values())

    # partition the leader away; remaining two elect a new leader
    c.partitioned.add(old_leader.node.id)
    c.run(60)
    new_leader = c.leader
    assert new_leader is not None and new_leader is not old_leader

    new_leader.order(make_env(b"c"))
    new_leader.order(make_env(b"d"))
    c.run(10)
    live = [ch for i, ch in c.chains.items() if i not in c.partitioned]
    assert all(ch.height == 2 for ch in live)
    # chain continuity on the survivors
    b1 = live[0].get_block(1)
    b0 = live[0].get_block(0)
    assert b1.header.previous_hash == protoutil.block_header_hash(b0.header)

    # heal the partition: old leader catches up
    c.partitioned.clear()
    c.run(30)
    assert c.chains[old_leader.node.id].height == 2


def test_wal_recovery(tmp_path):
    wal = WAL(str(tmp_path / "w" / "wal.log"))
    wal.save((3, 2), [Entry(1, 1, 0, b"x"), Entry(2, 3, 0, b"y")])
    wal.save(None, [Entry(3, 3, 0, b"z")])
    wal.close()
    hard, entries = wal.replay()
    assert hard == (3, 2)
    assert [e.index for e in entries] == [1, 2, 3]
    assert entries[2].data == b"z"

    # torn tail is dropped
    with open(str(tmp_path / "w" / "wal.log"), "ab") as f:
        f.write(b"\x99\x00\x00\x00partial")
    hard, entries = wal.replay()
    assert len(entries) == 3


def test_wal_conflicting_rewrite_keeps_latest(tmp_path):
    wal = WAL(str(tmp_path / "w2" / "wal.log"))
    wal.save(None, [Entry(1, 1, 0, b"old1"), Entry(2, 1, 0, b"old2")])
    wal.save(None, [Entry(2, 2, 0, b"new2")])  # term-2 leader overwrote idx 2
    _, entries = wal.replay()
    assert [(e.index, e.data) for e in entries] == [(1, b"old1"), (2, b"new2")]


def test_chain_restart_recovers_from_wal(tmp_path):
    ids = (1,)
    chain = RaftChain(
        "ch", 1, ids, wal_dir=str(tmp_path / "solo"),
        batch_config=BatchConfig(max_message_count=1), snapshot_interval=0,
    )
    chain.run_ticks = None
    for _ in range(30):
        chain.tick()
    assert chain.node.role == "leader"
    chain.order(make_env(b"tx1"))
    chain.order(make_env(b"tx2"))
    chain._pump()
    assert chain.height == 2
    chain.wal.close()

    again = RaftChain(
        "ch", 1, ids, wal_dir=str(tmp_path / "solo"),
        batch_config=BatchConfig(max_message_count=1), snapshot_interval=0,
    )
    # committed entries replay once the node re-commits them after election
    for _ in range(30):
        again.tick()
    assert again.node.role == "leader"
    again.order(make_env(b"tx3"))
    again._pump()
    assert again.height == 3
    assert again.get_block(2) is not None


def test_chain_restart_with_snapshot_keeps_height(tmp_path):
    """Regression: a restart with an on-disk snapshot must resume from the
    persisted block ledger, not silently reset to height 0 and re-mint
    already-used block numbers."""
    ids = (1,)
    chain = RaftChain(
        "ch", 1, ids, wal_dir=str(tmp_path / "snapchain"),
        batch_config=BatchConfig(max_message_count=1), snapshot_interval=2,
    )
    for _ in range(30):
        chain.tick()
    assert chain.node.role == "leader"
    for i in range(6):
        chain.order(make_env(f"tx{i}".encode()))
    chain._pump()
    assert chain.height == 6
    assert chain.node.snap_index > 0
    chain.wal.close()

    again = RaftChain(
        "ch", 1, ids, wal_dir=str(tmp_path / "snapchain"),
        batch_config=BatchConfig(max_message_count=1), snapshot_interval=2,
    )
    assert again.height == 6  # restored from the block ledger
    assert again.needs_catch_up is None
    for _ in range(30):
        again.tick()
    assert again.node.role == "leader"
    again.order(make_env(b"tx-after-restart"))
    again._pump()
    assert again.height == 7
    blk = again.get_block(6)
    assert blk is not None and blk.header.number == 6
    # the chain stays linked across the restart
    prev = again.get_block(5)
    from fabric_tpu.protos import protoutil as pu

    assert blk.header.previous_hash == pu.block_header_hash(prev.header)


def test_snapshot_compaction_and_catch_up(tmp_path):
    snap = SnapshotFile(str(tmp_path / "s" / "snapshot"))
    snap.save(7, 2, b"state")
    assert snap.load() == (7, 2, b"state")

    # cluster with snapshots every entry: lagging node gets a raft snapshot
    c = Cluster(tmp_path / "c")
    for ch in c.chains.values():
        ch.snapshot_interval = 2
    c.run(30)
    leader = c.leader
    lagger = next(
        ch for i, ch in c.chains.items() if ch is not leader
    )
    c.partitioned.add(lagger.node.id)
    for i in range(6):
        leader.order(make_env(b"x%d" % i))
    c.run(15)
    assert leader.height >= 3
    assert leader.node.snap_index > 0  # compaction happened

    c.partitioned.clear()
    c.run(40)
    # lagger's raft log caught up via snapshot; blocks must be pulled
    target = lagger.needs_catch_up
    if target is not None:
        missing = [
            leader.get_block(n) for n in range(lagger.height, target)
        ]
        lagger.catch_up([b for b in missing if b is not None])
    leader.order(make_env(b"y0"))
    leader.order(make_env(b"y1"))
    c.run(10)
    assert lagger.height == leader.height


def test_membership_eviction(tmp_path):
    c = Cluster(tmp_path)
    c.run(30)
    leader = c.leader
    victim = next(ch for ch in c.chains.values() if ch is not leader)
    keep = [i for i in c.chains if i != victim.node.id]
    leader.propose_conf_change(keep)
    c.run(10)
    assert victim.node.evicted
    # remaining cluster still makes progress
    leader.order(make_env(b"p"))
    leader.order(make_env(b"q"))
    c.run(10)
    live = [c.chains[i] for i in keep]
    assert all(ch.height >= 1 for ch in live)


# ---------------- wire codec bounds ----------------


def test_message_codec_rejects_inflated_wire_lengths():
    """Regression: message_from_bytes used to slice snap_data/entry data
    with decoded lengths verbatim — an inflated length silently returned
    a TRUNCATED blob as if it were whole, and an inflated entry count
    sized a loop off a u32 the peer chose. Every decoded length is now
    checked against the payload and rejected loudly."""
    from fabric_tpu.orderer.raft import Message, message_from_bytes, message_to_bytes

    m = Message(
        kind="snap", term=3, frm=1, to=2, snap_index=7, snap_term=2,
        snap_data=b"snapshot-bytes",
        entries=(Entry(8, 3, 0, b"payload"),),
    )
    raw = message_to_bytes(m)
    assert message_from_bytes(raw) == m  # round-trip intact

    head_len = struct.calcsize("<BQQQQQQBBQQQQ")
    # inflate snap_len past the end of the payload
    torn_snap = (
        raw[:head_len]
        + struct.pack("<QI", m.snap_term, len(raw))
        + raw[head_len + struct.calcsize("<QI"):]
    )
    with pytest.raises(ValueError, match="snapshot length"):
        message_from_bytes(torn_snap)

    # inflate the entry count: the loop must not run off the wire value
    n_off = head_len + struct.calcsize("<QI") + len(m.snap_data)
    huge_count = raw[:n_off] + struct.pack("<I", 2**31) + raw[n_off + 4:]
    with pytest.raises(ValueError, match="entry count"):
        message_from_bytes(huge_count)

    # inflate one entry's data length
    dlen_off = n_off + 4 + struct.calcsize("<QQB")
    torn_entry = (
        raw[:dlen_off] + struct.pack("<I", len(raw)) + raw[dlen_off + 4:]
    )
    with pytest.raises(ValueError, match="data length"):
        message_from_bytes(torn_entry)
