"""System chaincodes (qscc/cscc/lscc) + aclmgmt
(reference core/scc/qscc/query.go, core/scc/cscc/configure.go,
core/scc/lscc, core/aclmgmt)."""

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from fabric_tpu.chaincode.shim import ChaincodeStub
from fabric_tpu.chaincode.support import ChaincodeSupport, TxParams
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.peer.aclmgmt import (
    ACLError,
    ACLProvider,
    CSCC_GET_CHANNELS,
    PEER_PROPOSE,
    QSCC_GET_CHAIN_INFO,
)
from fabric_tpu.peer import Channel
from fabric_tpu.policy import from_dsl
from fabric_tpu.policy.manager import SignedData
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil
from fabric_tpu.scc import CSCC, LSCC, QSCC
from fabric_tpu.validation.validator import ChaincodeDefinition, ChaincodeRegistry

PROVIDER = SoftwareProvider()
CHANNEL = "scchannel"


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    """A committed chain with one real block so qscc has data to serve."""
    tmp = tmp_path_factory.mktemp("scc")
    org1 = generate_org("org1.example.com", "Org1MSP")
    mgr = MSPManager([org1.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("mycc", from_dsl("OR('Org1MSP.member')"))]
    )
    channel = Channel(CHANNEL, str(tmp), mgr, registry, PROVIDER)
    client = SigningIdentity(org1.users[0], PROVIDER)
    peer = SigningIdentity(org1.peers[0], PROVIDER)

    # genesis-ish block 0 then one endorsed tx in block 1
    from fabric_tpu.orderer import SoloChain
    from fabric_tpu.orderer.blockcutter import BatchConfig

    blocks = []
    chain = SoloChain(
        CHANNEL,
        signer=peer,
        batch_config=BatchConfig(max_message_count=1),
        deliver=blocks.append,
    )
    results = serialize_tx_rwset(
        rw.TxRwSet(
            (rw.NsRwSet("mycc", (), (rw.KVWrite("k", False, b"v"),)),)
        )
    )
    bundle = create_proposal(client, CHANNEL, "mycc", [b"put", b"k"])
    env = create_signed_tx(
        bundle, client, [endorse_proposal(bundle, peer, results)]
    )
    chain.order(env)
    for b in blocks:
        channel.store_block(b)
    return {
        "channel": channel,
        "org1": org1,
        "client": client,
        "tx_id": bundle.tx_id,
        "blocks": blocks,
    }


def _run(cc, args, channel):
    sim = TxSimulator(channel.ledger.state_db, tx_id="q")
    support = ChaincodeSupport()
    stub = ChaincodeStub("qscc", CHANNEL, "q", args, sim, support=support)
    return cc.invoke(stub)


def test_qscc_chain_info(net):
    qscc = QSCC(lambda cid: net["channel"].ledger if cid == CHANNEL else None)
    resp = _run(qscc, [b"GetChainInfo", CHANNEL.encode()], net["channel"])
    assert resp.status == 200, resp.message
    info = protoutil.unmarshal(common_pb2.BlockchainInfo, resp.payload)
    assert info.height == 1
    assert info.currentBlockHash


def test_qscc_block_by_number_and_hash(net):
    qscc = QSCC(lambda cid: net["channel"].ledger if cid == CHANNEL else None)
    resp = _run(qscc, [b"GetBlockByNumber", CHANNEL.encode(), b"0"], net["channel"])
    assert resp.status == 200
    block = protoutil.unmarshal(common_pb2.Block, resp.payload)
    assert block.header.number == 0
    h = protoutil.block_header_hash(block.header)
    resp2 = _run(qscc, [b"GetBlockByHash", CHANNEL.encode(), h], net["channel"])
    assert resp2.status == 200
    assert protoutil.unmarshal(common_pb2.Block, resp2.payload).header.number == 0
    resp3 = _run(
        qscc, [b"GetBlockByNumber", CHANNEL.encode(), b"99"], net["channel"]
    )
    assert resp3.status == 500


def test_qscc_transaction_by_id(net):
    qscc = QSCC(lambda cid: net["channel"].ledger if cid == CHANNEL else None)
    resp = _run(
        qscc,
        [b"GetTransactionByID", CHANNEL.encode(), net["tx_id"].encode()],
        net["channel"],
    )
    assert resp.status == 200, resp.message
    pt = protoutil.unmarshal(peer_pb2.ProcessedTransaction, resp.payload)
    assert pt.validationCode == 0  # VALID
    resp2 = _run(
        qscc, [b"GetTransactionByID", CHANNEL.encode(), b"nope"], net["channel"]
    )
    assert resp2.status == 500


def test_qscc_rejects_unknown_channel_and_fn(net):
    qscc = QSCC(lambda cid: None)
    resp = _run(qscc, [b"GetChainInfo", b"nochannel"], net["channel"])
    assert resp.status == 500
    qscc2 = QSCC(lambda cid: net["channel"].ledger)
    resp2 = _run(qscc2, [b"Bogus", CHANNEL.encode(), b"x"], net["channel"])
    assert resp2.status == 500


def test_cscc_channels_and_join(net):
    joined = []
    cscc = CSCC(
        join_chain=joined.append,
        channel_list=lambda: [CHANNEL],
        get_config_block=lambda cid: net["blocks"][0]
        if cid == CHANNEL
        else None,
    )
    resp = _run(cscc, [b"GetChannels"], net["channel"])
    assert resp.status == 200
    channels = protoutil.unmarshal(peer_pb2.ChannelQueryResponse, resp.payload)
    assert [c.channel_id for c in channels.channels] == [CHANNEL]

    block = net["blocks"][0]
    resp = _run(cscc, [b"JoinChain", block.SerializeToString()], net["channel"])
    assert resp.status == 200
    assert len(joined) == 1 and joined[0].header.number == 0

    resp = _run(cscc, [b"GetConfigBlock", CHANNEL.encode()], net["channel"])
    assert resp.status == 200


def test_lscc_queries(net):
    lscc = LSCC(lambda: [("mycc", "1.0"), ("asset", "2.1")])
    resp = _run(lscc, [b"getchaincodes"], net["channel"])
    assert resp.status == 200
    q = protoutil.unmarshal(peer_pb2.ChaincodeQueryResponse, resp.payload)
    assert [(c.name, c.version) for c in q.chaincodes] == [
        ("asset", "2.1"),
        ("mycc", "1.0"),
    ]
    resp = _run(lscc, [b"getccdata", CHANNEL.encode(), b"mycc"], net["channel"])
    assert resp.status == 200
    resp = _run(lscc, [b"getccdata", CHANNEL.encode(), b"nope"], net["channel"])
    assert resp.status == 500


# ---------------- aclmgmt ----------------


@pytest.fixture(scope="module")
def acl_world():
    org1 = generate_org("org1.example.com", "Org1MSP")
    org2 = generate_org("org2.example.com", "Org2MSP")
    from fabric_tpu.channelconfig import (
        ApplicationProfile,
        OrganizationProfile,
        Profile,
        genesis_block,
    )
    from fabric_tpu.channelconfig.bundle import bundle_from_genesis_block

    profile = Profile(
        application=ApplicationProfile(
            organizations=[
                OrganizationProfile("Org1MSP", org1.msp_config()),
            ]
        )
    )
    bundle = bundle_from_genesis_block(genesis_block(profile, "aclchannel"))
    return org1, org2, bundle


def _sd(node, msg=b"payload"):
    s = SigningIdentity(node, PROVIDER)
    return SignedData(msg, s.serialize(), s.sign(msg))


def test_acl_default_allows_member_reads(acl_world):
    org1, _, bundle = acl_world
    acl = ACLProvider(lambda cid: bundle.policy_manager)
    acl.check_acl(QSCC_GET_CHAIN_INFO, "aclchannel", [_sd(org1.peers[0])])
    acl.check_acl(PEER_PROPOSE, "aclchannel", [_sd(org1.users[0])])


def test_acl_rejects_non_member(acl_world):
    _, org2, bundle = acl_world
    acl = ACLProvider(lambda cid: bundle.policy_manager)
    with pytest.raises(ACLError):
        acl.check_acl(QSCC_GET_CHAIN_INFO, "aclchannel", [_sd(org2.peers[0])])


def test_acl_unknown_resource_and_channel(acl_world):
    org1, _, bundle = acl_world
    acl = ACLProvider(lambda cid: bundle.policy_manager if cid == "aclchannel" else None)
    with pytest.raises(ACLError):
        acl.check_acl("no/such/resource", "aclchannel", [_sd(org1.peers[0])])
    with pytest.raises(ACLError):
        acl.check_acl(QSCC_GET_CHAIN_INFO, "otherchannel", [_sd(org1.peers[0])])


def test_acl_config_override(acl_world):
    org1, _, bundle = acl_world
    # override GetChainInfo to require Admins: a peer (member) is rejected
    acl = ACLProvider(
        lambda cid: bundle.policy_manager,
        acl_overrides=lambda cid: {QSCC_GET_CHAIN_INFO: "Admins"},
    )
    with pytest.raises(ACLError):
        acl.check_acl(QSCC_GET_CHAIN_INFO, "aclchannel", [_sd(org1.peers[0])])
    acl.check_acl(QSCC_GET_CHAIN_INFO, "aclchannel", [_sd(org1.admin)])


def test_acl_local_policy_routes_to_local_check(acl_world):
    org1, _, bundle = acl_world
    calls = []
    acl = ACLProvider(
        lambda cid: bundle.policy_manager,
        local_check=lambda policy, sd: calls.append(policy),
    )
    acl.check_acl(CSCC_GET_CHANNELS, "", [_sd(org1.peers[0])])
    assert calls == ["Members"]


# ---------------- lscc legacy deploy/upgrade (lscc.go :580) ----------------


def _lscc_stub(net, args, sim=None):
    from fabric_tpu.ledger.simulator import TxSimulator

    sim = sim or TxSimulator(net["channel"].ledger.state_db, tx_id="d")
    support = ChaincodeSupport()
    return ChaincodeStub("lscc", CHANNEL, "d", args, sim, support=support), sim


def _depspec(name, version, pkg=b"code"):
    spec = peer_pb2.ChaincodeDeploymentSpec()
    spec.chaincode_spec.chaincode_id.name = name
    spec.chaincode_spec.chaincode_id.version = version
    spec.code_package = pkg
    return spec.SerializeToString()


def test_lscc_deploy_writes_chaincode_data_and_collections(net):
    from fabric_tpu.ledger.collections import build_collection_config_package
    from fabric_tpu.validation.legacy import check_v13_writeset

    lscc = LSCC(lambda: [])
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.policy.proto_convert import marshal_envelope

    colls = build_collection_config_package(
        [{"name": "secret", "policy": "OR('Org1MSP.member')"}]
    ).SerializeToString()
    policy = marshal_envelope(from_dsl("OR('Org1MSP.member')"))
    stub, sim = _lscc_stub(
        net,
        [b"deploy", CHANNEL.encode(), _depspec("legacycc", "1.0"),
         policy, b"escc", b"vscc", colls],
    )
    resp = lscc.invoke(stub)
    assert resp.status == 200, resp.message
    cd = peer_pb2.ChaincodeData()
    cd.ParseFromString(resp.payload)
    assert (cd.name, cd.version, cd.escc) == ("legacycc", "1.0", "escc")
    # the produced write-set is exactly what the v13 guard accepts
    rwset = sim.get_tx_simulation_results().rwset
    assert check_v13_writeset(rwset, "lscc") is None
    writes = {
        w.key for ns in rwset.ns_rw_sets if ns.namespace == "lscc"
        for w in ns.writes
    }
    assert writes == {"legacycc", "legacycc~collection"}


def _policy_bytes():
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.policy.proto_convert import marshal_envelope

    return marshal_envelope(from_dsl("OR('Org1MSP.member')"))


def test_lscc_deploy_validation_errors(net):
    lscc = LSCC(lambda: [])
    stub, _ = _lscc_stub(
        net,
        [b"deploy", CHANNEL.encode(), _depspec("bad name!", "1.0"),
         _policy_bytes()],
    )
    assert lscc.invoke(stub).status == 500
    # policy REQUIRED and must parse (an empty/garbage policy would
    # brick the chaincode at validation time)
    stub, _ = _lscc_stub(
        net, [b"deploy", CHANNEL.encode(), _depspec("okcc", "1.0")]
    )
    assert lscc.invoke(stub).status == 500
    stub, _ = _lscc_stub(
        net,
        [b"deploy", CHANNEL.encode(), _depspec("okcc", "1.0"), b"\xff\x01"],
    )
    assert lscc.invoke(stub).status == 500
    stub, _ = _lscc_stub(
        net,
        [b"deploy", CHANNEL.encode(), _depspec("cc", "bad version!"),
         _policy_bytes()],
    )
    assert lscc.invoke(stub).status == 500
    stub, _ = _lscc_stub(net, [b"deploy", CHANNEL.encode(), b"\xff\xfe"])
    assert lscc.invoke(stub).status == 500
    # V2_0 channels refuse legacy deploys
    lscc_v2 = LSCC(lambda: [], v20_active=lambda cid: True)
    stub, _ = _lscc_stub(
        net,
        [b"deploy", CHANNEL.encode(), _depspec("cc", "1.0"), _policy_bytes()],
    )
    resp = lscc_v2.invoke(stub)
    assert resp.status == 500 and "lifecycle" in resp.message


def test_lscc_upgrade_rules(net):
    from fabric_tpu.ledger.rwset import Version
    from fabric_tpu.ledger.statedb import UpdateBatch

    lscc = LSCC(lambda: [])
    # commit a deployed record directly into state
    cd = peer_pb2.ChaincodeData(name="upcc", version="1.0")
    batch = UpdateBatch()
    batch.put("lscc", "upcc", cd.SerializeToString(), Version(9, 0))
    net["channel"].ledger.state_db.apply_updates(batch)

    # same-version upgrade refused
    stub, _ = _lscc_stub(
        net,
        [b"upgrade", CHANNEL.encode(), _depspec("upcc", "1.0"),
         _policy_bytes()],
    )
    assert lscc.invoke(stub).status == 500
    # upgrade of a non-existent chaincode refused
    stub, _ = _lscc_stub(
        net,
        [b"upgrade", CHANNEL.encode(), _depspec("ghost", "2.0"),
         _policy_bytes()],
    )
    assert lscc.invoke(stub).status == 500
    # proper upgrade succeeds and get queries see committed records
    stub, sim = _lscc_stub(
        net,
        [b"upgrade", CHANNEL.encode(), _depspec("upcc", "2.0"),
         _policy_bytes()],
    )
    resp = lscc.invoke(stub)
    assert resp.status == 200, resp.message
    # getccdata returns the committed ChaincodeData bytes
    stub2, _ = _lscc_stub(net, [b"getccdata", CHANNEL.encode(), b"upcc"])
    resp = lscc.invoke(stub2)
    assert resp.status == 200
    got = peer_pb2.ChaincodeData()
    got.ParseFromString(resp.payload)
    assert (got.name, got.version) == ("upcc", "1.0")  # still the committed one
