"""Minimal end-to-end network: client -> solo orderer -> two peers.

The NWO-analog smoke test (reference integration/e2e): endorse real
transactions, order them into signed blocks, run the full peer commit
pipeline on two independent peers, and check that state, the
TRANSACTIONS_FILTER and the chained COMMIT_HASH agree byte-for-byte
(cross-peer state-divergence detection, kv_ledger.go:630-636).
"""

import pytest

pytest.importorskip(
    "cryptography", reason="network e2e generates X.509 crypto-config"
)

from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.orderer import SoloChain
from fabric_tpu.orderer.blockcutter import BatchConfig
from fabric_tpu.peer import Channel
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import common_pb2, protoutil
from fabric_tpu.validation.txflags import TxValidationCode
from fabric_tpu.validation.validator import ChaincodeDefinition, ChaincodeRegistry

CHANNEL = "e2echannel"
PROVIDER = SoftwareProvider()


@pytest.fixture(scope="module")
def net():
    org1 = generate_org("org1.example.com", "Org1MSP")
    org2 = generate_org("org2.example.com", "Org2MSP")
    orderer_org = generate_org("orderer.example.com", "OrdererMSP")
    mgr = MSPManager([org1.msp(provider=PROVIDER), org2.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("mycc", from_dsl("AND('Org1MSP.member','Org2MSP.member')"))]
    )
    return {
        "mgr": mgr,
        "registry": registry,
        "client": SigningIdentity(org1.users[0], PROVIDER),
        "p1": SigningIdentity(org1.peers[0], PROVIDER),
        "p2": SigningIdentity(org2.peers[0], PROVIDER),
        "oid": SigningIdentity(orderer_org.peers[0], PROVIDER),
    }


def invoke(net, key, value, reads=()):
    results = serialize_tx_rwset(
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "mycc",
                    tuple(rw.KVRead(k, v) for k, v in reads),
                    (rw.KVWrite(key, False, value),),
                ),
            )
        )
    )
    bundle = create_proposal(net["client"], CHANNEL, "mycc", [b"put", key.encode()])
    responses = [
        endorse_proposal(bundle, net["p1"], results),
        endorse_proposal(bundle, net["p2"], results),
    ]
    return create_signed_tx(bundle, net["client"], responses)


def test_full_pipeline_two_peers(net, tmp_path):
    delivered = []
    chain = SoloChain(
        CHANNEL,
        signer=net["oid"],
        batch_config=BatchConfig(max_message_count=3),
        deliver=delivered.append,
    )

    peers = [
        Channel(CHANNEL, str(tmp_path / f"peer{i}"), net["mgr"], net["registry"], PROVIDER)
        for i in range(2)
    ]

    # 6 txs -> two blocks of 3; tx 4 reads a key at a stale version -> MVCC
    envs = [invoke(net, f"k{i}", f"v{i}".encode()) for i in range(3)]
    # k0 was committed at height (0,0), k1 at (0,1): tx index = position in block
    envs.append(invoke(net, "k9", b"x", reads=[("k0", rw.Version(0, 2))]))  # stale
    envs.append(invoke(net, "k1", b"v1b", reads=[("k1", rw.Version(0, 1))]))  # correct
    envs.append(invoke(net, "k5", b"v5"))
    for env in envs:
        chain.order(env)
    assert len(delivered) == 2

    for block in delivered:
        for peer in peers:
            peer.store_block(common_pb2.Block.FromString(block.SerializeToString()))

    V = TxValidationCode
    for peer in peers:
        assert peer.height == 2
        assert peer.ledger.get_state("mycc", "k0") == b"v0"
        assert peer.ledger.get_state("mycc", "k1") == b"v1b"  # updated by tx4
        assert peer.ledger.get_state("mycc", "k9") is None  # MVCC-invalidated
        assert peer.ledger.get_state("mycc", "k5") == b"v5"

    # stored filter: block 2 = [MVCC_READ_CONFLICT, VALID, VALID]
    stored = peers[0].ledger.block_store.get_block_by_number(1)
    assert list(stored.metadata.metadata[common_pb2.TRANSACTIONS_FILTER]) == [
        int(V.MVCC_READ_CONFLICT),
        int(V.VALID),
        int(V.VALID),
    ]

    # commit hashes identical across peers (divergence detector)
    assert peers[0].ledger.commit_hash == peers[1].ledger.commit_hash
    assert len(peers[0].ledger.commit_hash) == 32

    # history index
    assert [v.block_num for v in peers[0].ledger.get_history_for_key("mycc", "k1")] == [0, 1]


def test_recovery_replays_block_store(net, tmp_path):
    chain = SoloChain(CHANNEL, signer=net["oid"], batch_config=BatchConfig(max_message_count=1))
    blocks = []
    chain.deliver = blocks.append
    chain.order(invoke(net, "ka", b"1"))
    chain.order(invoke(net, "ka", b"2"))

    path = str(tmp_path / "peer")
    peer = Channel(CHANNEL, path, net["mgr"], net["registry"], PROVIDER)
    for b in blocks:
        peer.store_block(b)
    want_hash = peer.ledger.block_store.last_block_hash
    peer.ledger.block_store.close()

    # fresh process: state rebuilt from the chain file alone
    peer2 = Channel(CHANNEL, path, net["mgr"], net["registry"], PROVIDER)
    assert peer2.height == 2
    assert peer2.ledger.get_state("mycc", "ka") == b"2"
    assert peer2.ledger.block_store.last_block_hash == want_hash


def test_commit_hash_chain_survives_restart(net, tmp_path):
    """A peer that restarts mid-chain must keep chaining COMMIT_HASH from
    the stored value — otherwise the divergence detector false-positives
    against a peer that never restarted."""
    chain = SoloChain(CHANNEL, signer=net["oid"], batch_config=BatchConfig(max_message_count=1))
    blocks = []
    chain.deliver = blocks.append
    for i in range(3):
        chain.order(invoke(net, f"kr{i}", str(i).encode()))

    steady = Channel(CHANNEL, str(tmp_path / "steady"), net["mgr"], net["registry"], PROVIDER)
    for b in blocks:
        steady.store_block(common_pb2.Block.FromString(b.SerializeToString()))

    path = str(tmp_path / "restarting")
    restarting = Channel(CHANNEL, path, net["mgr"], net["registry"], PROVIDER)
    for b in blocks[:2]:
        restarting.store_block(common_pb2.Block.FromString(b.SerializeToString()))
    restarting.ledger.block_store.close()

    reopened = Channel(CHANNEL, path, net["mgr"], net["registry"], PROVIDER)
    reopened.store_block(common_pb2.Block.FromString(blocks[2].SerializeToString()))
    assert reopened.ledger.commit_hash == steady.ledger.commit_hash


def test_tampered_block_rejected(net, tmp_path):
    from fabric_tpu.peer.channel import BlockVerificationError

    chain = SoloChain(CHANNEL, signer=net["oid"], batch_config=BatchConfig(max_message_count=1))
    blocks = []
    chain.deliver = blocks.append
    chain.order(invoke(net, "kb", b"1"))
    block = blocks[0]
    block.data.data[0] = block.data.data[0] + b"tampered"
    peer = Channel(CHANNEL, str(tmp_path / "peer"), net["mgr"], net["registry"], PROVIDER)
    with pytest.raises(BlockVerificationError):
        peer.store_block(block)


def test_orderer_signature_verified(net, tmp_path):
    chain = SoloChain(CHANNEL, signer=net["oid"], batch_config=BatchConfig(max_message_count=1))
    blocks = []
    chain.deliver = blocks.append
    chain.order(invoke(net, "kc", b"1"))
    block = blocks[0]

    def verify_sig(b):
        meta = protoutil.unmarshal(
            common_pb2.Metadata, b.metadata.metadata[common_pb2.SIGNATURES]
        )
        if not meta.signatures:
            return False
        sig = meta.signatures[0]
        shdr = protoutil.unmarshal(common_pb2.SignatureHeader, sig.signature_header)
        signed = meta.value + sig.signature_header + protoutil.block_header_bytes(b.header)
        from fabric_tpu.crypto.bccsp import VerifyError
        from fabric_tpu.msp.identity import Identity
        from cryptography import x509
        from fabric_tpu.protos import identities_pb2

        sid = protoutil.unmarshal(identities_pb2.SerializedIdentity, shdr.creator)
        cert = x509.load_pem_x509_certificate(sid.id_bytes)
        ident = Identity(sid.mspid, cert, PROVIDER)
        try:
            ident.verify(signed, sig.signature)
            return True
        except Exception:
            return False

    peer = Channel(
        CHANNEL,
        str(tmp_path / "peer"),
        net["mgr"],
        net["registry"],
        PROVIDER,
        verify_orderer_sig=verify_sig,
    )
    peer.store_block(block)
    assert peer.height == 1

    # a block with a corrupted signature is rejected
    chain.order(invoke(net, "kd", b"2"))
    bad = blocks[1]
    meta = protoutil.unmarshal(
        common_pb2.Metadata, bad.metadata.metadata[common_pb2.SIGNATURES]
    )
    meta.signatures[0].signature = b"\x30\x06\x02\x01\x01\x02\x01\x01"
    bad.metadata.metadata[common_pb2.SIGNATURES] = meta.SerializeToString()
    from fabric_tpu.peer.channel import BlockVerificationError

    with pytest.raises(BlockVerificationError):
        peer.store_block(bad)
