"""Differential tests: native C++ block parser vs the Python per-tx
parser (native/blockparse.cc vs validation/msgvalidation.py).

The native parser re-implements upb's wire acceptance by hand, so every
divergence class gets fuzzed: random byte mutations of well-formed
envelopes, truncations, wire-type rewrites, and structured corpus cases
(merge semantics, unknown groups, bad UTF-8, overlong varints).
"""

from __future__ import annotations

import hashlib
import random

import pytest

from fabric_tpu.utils import native as natmod
from fabric_tpu.validation import blockparse
from fabric_tpu.validation.msgvalidation import parse_transaction
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil

pytestmark = pytest.mark.skipif(
    not blockparse.available(), reason="native block parser not built"
)


# ----------------------------------------------------------------------
# corpus builders
# ----------------------------------------------------------------------


def _ld(field: int, b: bytes) -> bytes:
    """length-delimited field encoder (small payloads)"""
    out = bytearray([field << 3 | 2])
    n = len(b)
    while True:
        if n < 0x80:
            out.append(n)
            break
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    return bytes(out) + b


def _varint_field(field: int, v: int) -> bytes:
    out = bytearray([field << 3 | 0])
    while True:
        if v < 0x80:
            out.append(v)
            break
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    return bytes(out)


def make_endorser_tx(
    rng: random.Random,
    n_endorsements: int = 2,
    channel: str = "ch1",
    valid_txid: bool = True,
    valid_phash: bool = True,
    rwset: bytes = b"",
) -> bytes:
    creator = b"creator-" + rng.randbytes(8)
    nonce = rng.randbytes(16)
    tx_id = (
        hashlib.sha256(nonce + creator).hexdigest()
        if valid_txid
        else "deadbeef" * 8
    )
    chdr = common_pb2.ChannelHeader(
        type=common_pb2.ENDORSER_TRANSACTION,
        channel_id=channel,
        tx_id=tx_id,
        epoch=0,
    )
    shdr = common_pb2.SignatureHeader(creator=creator, nonce=nonce)
    act_shdr = common_pb2.SignatureHeader(
        creator=b"act-creator", nonce=b"act-nonce"
    )

    cc_action = peer_pb2.ChaincodeAction(results=rwset)
    cc_action.chaincode_id.name = "mycc"
    cpp = b"chaincode-proposal-payload-" + rng.randbytes(4)
    phash = hashlib.sha256(
        chdr.SerializeToString() + act_shdr.SerializeToString() + cpp
    ).digest()
    prp = peer_pb2.ProposalResponsePayload(
        proposal_hash=phash if valid_phash else b"\x00" * 32,
        extension=cc_action.SerializeToString(),
    )
    cap = peer_pb2.ChaincodeActionPayload(chaincode_proposal_payload=cpp)
    cap.action.proposal_response_payload = prp.SerializeToString()
    for e in range(n_endorsements):
        end = cap.action.endorsements.add()
        end.endorser = b"endorser-%d-" % e + rng.randbytes(6)
        end.signature = rng.randbytes(70)
    action = peer_pb2.TransactionAction(
        header=act_shdr.SerializeToString(), payload=cap.SerializeToString()
    )
    tx = peer_pb2.Transaction(actions=[action])
    payload = common_pb2.Payload(data=tx.SerializeToString())
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = shdr.SerializeToString()
    env = common_pb2.Envelope(
        payload=payload.SerializeToString(), signature=rng.randbytes(64)
    )
    return env.SerializeToString()


def make_rwset(rng: random.Random, with_md: bool = False) -> bytes:
    from fabric_tpu.ledger import rwset as rw
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset

    md = (rw.KVMetadataWrite("mk", (("p", b"v"),)),) if with_md else ()
    return serialize_tx_rwset(
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "mycc",
                    (rw.KVRead("rk", rw.Version(1, 2)),),
                    (rw.KVWrite("wk%d" % rng.randrange(99), False, b"v"),),
                    (),
                    (
                        rw.CollHashedRwSet(
                            "coll1",
                            (rw.KVReadHash(b"\x01" * 32, None),),
                            (rw.KVWriteHash(b"\x02" * 32, False, b"\x03" * 32),),
                            (),
                        ),
                    ),
                    md,
                ),
                rw.NsRwSet("other", (), (rw.KVWrite("ok", False, b"x"),)),
            )
        )
    )


def make_config_tx(rng: random.Random) -> bytes:
    chdr = common_pb2.ChannelHeader(
        type=common_pb2.CONFIG, channel_id="ch1", tx_id="cfg", epoch=0
    )
    shdr = common_pb2.SignatureHeader(creator=b"cfg-creator", nonce=b"n0")
    payload = common_pb2.Payload(data=b"config-bytes")
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = shdr.SerializeToString()
    env = common_pb2.Envelope(
        payload=payload.SerializeToString(), signature=b"sig"
    )
    return env.SerializeToString()


# ----------------------------------------------------------------------
# comparison
# ----------------------------------------------------------------------


def assert_parse_equal(datas):
    got = blockparse.parse_block(datas)
    assert got.native, "native parser did not run"
    want = [parse_transaction(i, d) for i, d in enumerate(datas)]
    for g, w in zip(got, want):
        ctx = f"tx {w.index} code={w.code!r}"
        assert g.code == w.code, ctx
        assert g.header_type == w.header_type, ctx
        assert g.channel_id == w.channel_id, ctx
        assert g.tx_id == w.tx_id, ctx
        assert g.creator == w.creator, ctx
        assert g.namespace == w.namespace, ctx
        assert g.config_data == w.config_data, ctx
        # creator signature job
        if w.creator_sig_job is None:
            assert g.creator_sig_job is None, ctx
        else:
            assert g.creator_sig_job is not None, ctx
            assert (
                g.creator_sig_job.identity_bytes
                == w.creator_sig_job.identity_bytes
            ), ctx
            assert g.creator_sig_job.signature == w.creator_sig_job.signature, ctx
            assert (
                g.creator_sig_job.digest
                == hashlib.sha256(w.creator_sig_job.data).digest()
            ), ctx
        # endorsement jobs
        assert len(g.endorsement_jobs) == len(w.endorsement_jobs), ctx
        for gj, wj in zip(g.endorsement_jobs, w.endorsement_jobs):
            assert gj.identity_bytes == wj.identity_bytes, ctx
            assert gj.signature == wj.signature, ctx
            assert gj.digest == hashlib.sha256(wj.data).digest(), ctx
        # rwset: lazy materialization must agree with the eager parse
        assert g.rwset == w.rwset, ctx
        assert g.ns_entries == w.ns_entries, ctx
        assert g.has_md_writes == w.has_md_writes, ctx
    return got, want


# ----------------------------------------------------------------------
# tests
# ----------------------------------------------------------------------


def test_valid_block_roundtrip():
    rng = random.Random(7)
    datas = [make_endorser_tx(rng, rwset=make_rwset(rng)) for _ in range(8)]
    datas.append(make_config_tx(rng))
    datas.append(b"")  # NIL_ENVELOPE
    datas.append(make_endorser_tx(rng, valid_txid=False))
    datas.append(make_endorser_tx(rng, valid_phash=False))
    datas.append(make_endorser_tx(rng, rwset=make_rwset(rng, with_md=True)))
    got, _ = assert_parse_equal(datas)
    # metadata-write flag must surface for the SBE gate
    assert got[len(datas) - 1].has_md_writes


def test_written_keys_table():
    rng = random.Random(8)
    datas = [make_endorser_tx(rng, rwset=make_rwset(rng)) for _ in range(3)]
    got = blockparse.parse_block(datas)
    keys = list(got.iter_written_keys())
    # per tx: 1 public write in mycc, 1 hashed write in coll1, 1 public in other
    assert len(keys) == 9
    per_tx = [k for k in keys if k[0] == 0]
    assert {(ns, coll) for _i, ns, coll, _k in per_tx} == {
        ("mycc", ""),
        ("mycc", "coll1"),
        ("other", ""),
    }
    hashed = [k for _i, ns, coll, k in per_tx if coll == "coll1"]
    assert hashed == [b"\x02" * 32]


def test_structured_edge_cases():
    """Hand-built wire edge cases the fuzzer is unlikely to synthesize."""
    rng = random.Random(9)
    base = make_endorser_tx(rng, rwset=make_rwset(rng))

    cases = [b"", b"\x00", b"\xff" * 4, base + b"\x1a\x03abc"]
    # repeated Payload.header: proto3 merge
    chdr = common_pb2.ChannelHeader(
        type=common_pb2.CONFIG, channel_id="chX", tx_id="t", epoch=0
    )
    shdr = common_pb2.SignatureHeader(creator=b"c", nonce=b"n")
    h1 = common_pb2.Header(channel_header=chdr.SerializeToString())
    h2 = common_pb2.Header(signature_header=shdr.SerializeToString())
    merged_payload = (
        _ld(1, h1.SerializeToString())
        + _ld(1, h2.SerializeToString())
        + _ld(2, b"cfg")
    )
    cases.append(_ld(1, merged_payload) + _ld(2, b"s"))
    # unknown balanced group inside Envelope + junk fields
    grp = bytes([15 << 3 | 3]) + _varint_field(1, 5) + bytes([15 << 3 | 4])
    cases.append(grp + base)
    # unbalanced group -> envelope decode error
    cases.append(bytes([15 << 3 | 3]) + base)
    # balanced-group nesting at the python-protobuf recursion boundary
    # (upb accepts 100-deep, rejects 101): native must agree lane-exact
    for depth in (89, 90, 91, 99, 100, 101, 105):
        cases.append(
            bytes([15 << 3 | 3]) * depth
            + bytes([15 << 3 | 4]) * depth
            + base
        )
    # overlong varint (11 bytes)
    cases.append(bytes([0x08]) + b"\x80" * 10 + b"\x01")
    # wrong wire type on Envelope.payload (varint) -> field skipped
    cases.append(_varint_field(1, 7) + _ld(2, b"s"))
    # bad utf-8 in channel_id
    bad_chdr = (
        _varint_field(1, 3) + _ld(4, b"\xff\xfe") + _ld(5, b"t")
    )
    bad_header = _ld(1, bad_chdr) + _ld(2, shdr.SerializeToString())
    cases.append(_ld(1, _ld(1, bad_header) + _ld(2, b"d")) + _ld(2, b"s"))
    # epoch != 0
    echdr = common_pb2.ChannelHeader(
        type=common_pb2.CONFIG, channel_id="c", tx_id="t", epoch=5
    )
    ep = common_pb2.Payload(data=b"d")
    ep.header.channel_header = echdr.SerializeToString()
    ep.header.signature_header = shdr.SerializeToString()
    cases.append(
        common_pb2.Envelope(
            payload=ep.SerializeToString(), signature=b"s"
        ).SerializeToString()
    )
    # unsupported header type
    uchdr = common_pb2.ChannelHeader(type=99, channel_id="c", tx_id="t")
    up = common_pb2.Payload(data=b"d")
    up.header.channel_header = uchdr.SerializeToString()
    up.header.signature_header = shdr.SerializeToString()
    cases.append(
        common_pb2.Envelope(
            payload=up.SerializeToString(), signature=b"s"
        ).SerializeToString()
    )
    assert_parse_equal(cases)


def test_group_depth_parity_nested():
    """upb's recursion budget (100) accumulates across message levels
    below each ParseFromString root — groups inside SUBMESSAGES must hit
    the limit earlier than groups at the root, and the native walker
    must agree lane-exact at every boundary (review r5 counterexample:
    100-deep groups inside Header diverged)."""
    rng = random.Random(11)
    base = make_endorser_tx(rng, rwset=make_rwset(rng))
    env = common_pb2.Envelope()
    env.ParseFromString(base)
    payload = common_pb2.Payload()
    payload.ParseFromString(env.payload)

    def grp(depth):
        return bytes([15 << 3 | 3]) * depth + bytes([15 << 3 | 4]) * depth

    cases = []
    # Header sits at depth 1 under the Payload root: budget 99
    for d in (98, 99, 100, 101):
        hdr = payload.header.SerializeToString() + grp(d)
        p = _ld(1, hdr) + _ld(2, payload.data)
        cases.append(_ld(1, p) + _ld(2, b"s"))
    # Timestamp sits at depth 1 under the ChannelHeader root: budget 99
    chdr_bytes = payload.header.channel_header
    for d in (98, 99, 100):
        ch2 = chdr_bytes + _ld(3, grp(d))
        hdr = _ld(1, ch2) + _ld(2, payload.header.signature_header)
        p = _ld(1, hdr) + _ld(2, payload.data)
        cases.append(_ld(1, p) + _ld(2, b"s"))
    # KVRead.Version sits at depth 2 under the KVRWSet root: budget 98
    for d in (97, 98, 99):
        kvread = _ld(1, b"k") + _ld(2, grp(d))
        kvrwset = _ld(1, kvread)
        ns = _ld(1, b"mycc") + _ld(2, kvrwset)
        cases.append(make_endorser_tx(rng, rwset=_ld(2, ns)))
    assert_parse_equal(cases)


def test_lazy_rwset_divergence_degrades_to_bad_rwset():
    """If the native walker accepted rwset bytes the Python parser later
    rejects (acceptance divergence), the lazy materialization must mark
    THAT tx BAD_RWSET — never raise into the commit path (ADVICE r4)."""
    from fabric_tpu.validation.msgvalidation import ParsedTx
    from fabric_tpu.validation.txflags import TxValidationCode

    tx = ParsedTx(3)
    tx._rwset_raw = b"\xff\xff\xff\xff"  # not a TxReadWriteSet
    assert tx.rwset is None
    assert tx.code == TxValidationCode.BAD_RWSET
    assert tx.rwset is None  # cached; no re-parse attempt


def test_fuzz_mutations():
    """Random single/multi-byte mutations over valid envelopes: the two
    parsers must assign identical codes and artifacts for every mutant."""
    rng = random.Random(1234)
    originals = [
        make_endorser_tx(rng, rwset=make_rwset(rng)),
        make_endorser_tx(rng, n_endorsements=1),
        make_config_tx(rng),
    ]
    mutants = []
    for _ in range(400):
        base = bytearray(rng.choice(originals))
        kind = rng.randrange(4)
        if kind == 0:  # point mutation
            for _ in range(rng.randrange(1, 4)):
                base[rng.randrange(len(base))] = rng.randrange(256)
        elif kind == 1:  # truncation
            base = base[: rng.randrange(len(base))]
        elif kind == 2:  # random insertion
            pos = rng.randrange(len(base))
            base[pos:pos] = rng.randbytes(rng.randrange(1, 6))
        else:  # splice two envelopes
            other = rng.choice(originals)
            cut = rng.randrange(len(base))
            base = base[:cut] + other[cut:]
        mutants.append(bytes(base))
    assert_parse_equal(mutants)


def test_fuzz_random_bytes():
    rng = random.Random(99)
    blobs = [rng.randbytes(rng.randrange(0, 200)) for _ in range(300)]
    assert_parse_equal(blobs)


def test_sha_backend_reported():
    lib = natmod._load()
    assert lib is not None
    # informational: either backend is fine; the call must not crash
    assert lib.fn_sha256_backend() in (0, 1)
