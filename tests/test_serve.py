"""Resident validation sidecar (fabric_tpu.serve): protocol framing,
the bucketed AOT program registry (zero compiles in steady state, warm
restart from serialized executables), admission control, and the client
shim's fail-closed degrade ladder — masks bit-exact vs the in-process
path through every failure flavor, including sidecar kill mid-batch."""

import hashlib
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from fabric_tpu.common import p256
from fabric_tpu.crypto import der, hostec
from fabric_tpu.crypto.bccsp import ECDSAPublicKey, SoftwareProvider
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.client import (
    SidecarClient,
    SidecarProvider,
    SidecarUnavailable,
    encode_lanes,
)
from fabric_tpu.serve.registry import BucketProgramRegistry, bucket_for
from fabric_tpu.serve.server import SidecarServer, parse_address

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# workload material
# ---------------------------------------------------------------------------

_D_PRIV = 0xA1B2C3D4E5F6
_PUB = ECDSAPublicKey(*hostec.scalar_base_mult(_D_PRIV))

LANE_KINDS = ("good", "bad_sig", "high_s", "garbage", "no_key")


def mixed_lanes(n, seed=0):
    """(keys, sigs, digests, expected) with deterministic per-lane
    corruption kinds covering the parse, low-S and curve paths."""
    keys, sigs, digests, expected = [], [], [], []
    for i in range(n):
        digest = hashlib.sha256(b"serve lane %d %d" % (seed, i)).digest()
        r, s = hostec.sign_digest(_D_PRIV, digest)
        sig = der.marshal_signature(r, s)
        kind = LANE_KINDS[i % len(LANE_KINDS)]
        key = _PUB
        if kind == "bad_sig":
            bad = bytearray(sig)
            bad[-1] ^= 0x5A
            sig = bytes(bad)
        elif kind == "high_s":
            sig = der.marshal_signature(r, p256.N - s)
        elif kind == "garbage":
            sig = b"\x00\x01garbage"
        elif kind == "no_key":
            key = None
        keys.append(key)
        sigs.append(sig)
        digests.append(digest)
        expected.append(kind == "good")
    return keys, sigs, digests, expected


@pytest.fixture
def sidecar(tmp_path):
    """A warm host-engine sidecar on a unix socket + teardown."""
    addr = str(tmp_path / "serve.sock")
    server = SidecarServer(addr, engine="host", warm_ladder="off",
                           buckets=(64, 256))
    server.warm()
    server.start()
    yield server
    server.stop()


# ---------------------------------------------------------------------------
# protocol
# ---------------------------------------------------------------------------


class TestProtocol:
    def _pipe(self):
        a, b = socket.socketpair()
        return a, b

    def test_frame_roundtrip(self):
        a, b = self._pipe()
        proto.send_frame(a, proto.OP_VERIFY, 7, b"payload")
        opcode, req_id, payload = proto.recv_frame(b)
        assert (opcode, req_id, payload) == (proto.OP_VERIFY, 7, b"payload")
        a.close()
        assert proto.recv_frame(b) is None  # clean EOF

    def test_bad_magic_rejected(self):
        a, b = self._pipe()
        a.sendall(b"XX" + b"\x00" * (proto.HEADER_SIZE - 2))
        with pytest.raises(proto.ProtocolError, match="magic"):
            proto.recv_frame(b)

    def test_truncated_frame_rejected(self):
        a, b = self._pipe()
        frame = proto.pack_frame(proto.OP_PING, 1, b"full payload here")
        a.sendall(frame[:-5])
        a.close()
        with pytest.raises(proto.ProtocolError, match="mid-frame|payload"):
            proto.recv_frame(b)

    def test_oversized_frame_rejected(self):
        a, b = self._pipe()
        head = struct.pack(
            ">2sBBII", proto.MAGIC, proto.PROTOCOL_VERSION, proto.OP_VERIFY,
            1, proto.MAX_PAYLOAD + 1,
        )
        a.sendall(head)
        with pytest.raises(proto.ProtocolError, match="MAX_PAYLOAD"):
            proto.recv_frame(b)

    def test_verify_request_roundtrip(self):
        table = [b"\x04" + b"\x01" * 64, b"\x04" + b"\x02" * 64]
        lanes = [(0, b"sig0", b"d" * 32), (proto.NO_KEY, b"", b"e" * 32),
                 (1, b"sig2", b"f" * 32)]
        out_table, out_lanes, qos, chan, _dl = proto.decode_verify_request(
            proto.encode_verify_request(table, lanes)
        )
        assert out_table == table
        assert out_lanes == lanes
        assert (qos, chan) == (proto.DEFAULT_QOS, "")
        # protocol rev 2: the QoS prefix rides the same lane table
        out_table, out_lanes, qos, chan, _dl = proto.decode_verify_request(
            proto.encode_verify_request(
                table, lanes, qos_class=proto.QOS_HIGH, channel="paychan"
            ),
            version=2,
        )
        assert out_table == table
        assert out_lanes == lanes
        assert (qos, chan) == (proto.QOS_HIGH, "paychan")

    def test_verify_request_bad_key_index(self):
        payload = proto.encode_verify_request([b"k"], [(0, b"s", b"d")])
        # corrupt the lane's key index to 5 (only 1 key in the table)
        bad = bytearray(payload)
        off = 2 + 2 + 1 + 4  # n_keys + klen + key + n_lanes
        struct.pack_into(">H", bad, off, 5)
        with pytest.raises(proto.ProtocolError, match="out of range"):
            proto.decode_verify_request(bytes(bad))

    def test_verify_response_roundtrip(self):
        mask = [True, False, True]
        st, retry, out, msg = proto.decode_verify_response(
            proto.encode_verify_response(proto.ST_OK, mask=mask)
        )
        assert (st, out, msg) == (proto.ST_OK, mask, "")
        st, retry, out, msg = proto.decode_verify_response(
            proto.encode_verify_response(
                proto.ST_BUSY, message="full", retry_after_ms=40
            )
        )
        assert (st, retry, out, msg) == (proto.ST_BUSY, 40, None, "full")

    def test_encode_lanes_dedups_keys(self):
        keys, sigs, digests, _ = mixed_lanes(10)
        payload = encode_lanes(keys, sigs, digests)
        # encode_lanes defaults to the current-revision body
        table, lanes, _qos, _chan, _dl = proto.decode_verify_request(
            payload, version=proto.PROTOCOL_VERSION
        )
        assert len(table) == 1  # one distinct key object
        assert [i for i, _, _ in lanes].count(proto.NO_KEY) == 2  # no_key kind


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_bucket_for_ladder(self):
        assert bucket_for(1, (128, 256)) == 128
        assert bucket_for(128, (128, 256)) == 128
        assert bucket_for(129, (128, 256)) == 256
        assert bucket_for(300, (128, 256)) == 512  # oversize: top multiple

    def test_warm_once_and_lookup(self):
        built = []

        def builder(bucket):
            built.append(bucket)
            return (lambda: bucket), {}

        reg = BucketProgramRegistry((4, 8), builder, label="t")
        reg.warm()
        reg.warm()  # idempotent
        assert built == [4, 8]
        b, program = reg.program_for(3)
        assert (b, program()) == (4, 4)
        assert reg.program_for(8)[0] == 8

    def test_unwarmed_bucket_is_an_error_not_a_compile(self):
        reg = BucketProgramRegistry((4,), lambda b: ((lambda: b), {}))
        with pytest.raises(KeyError, match="not warmed"):
            reg.program_for(2)

    def test_ladder_must_be_sorted_unique(self):
        with pytest.raises(ValueError):
            BucketProgramRegistry((8, 4), lambda b: ((lambda: b), {}))


class TestJaxRegistry:
    """The AOT path with the real (small) ops.bignum demo program."""

    BUCKETS = (8,)

    def _registry(self, aot_dir=None):
        from fabric_tpu.serve.registry import demo_limb_program

        fn, shapes_for = demo_limb_program()
        return BucketProgramRegistry.for_jax_program(
            fn, shapes_for, buckets=self.BUCKETS, label="test-demo",
            aot_dir=aot_dir,
        )

    def test_steady_state_zero_compiles(self):
        """The acceptance gate: after warm(), dispatching many requests
        across the ladder triggers ZERO re-traces and ZERO XLA compile
        events — asserted by the registry's trace counter AND the
        process-wide jax compile-event counters."""
        import numpy as np

        from fabric_tpu.serve.registry import _CompileCounters

        reg = self._registry()
        reg.warm()
        traces0 = reg.traces
        c0, _h0 = _CompileCounters.snapshot()
        bucket, program = reg.program_for(5)
        x = np.arange(20 * bucket, dtype=np.uint32).reshape(20, bucket) % 8191
        ref = np.asarray(program(x))
        for _ in range(12):
            bucket, program = reg.program_for(3 + (_ % 6))
            out = np.asarray(program(x))
            assert (out == ref).all()
        c1, _h1 = _CompileCounters.snapshot()
        assert reg.traces == traces0, "steady state re-traced a program"
        assert c1 == c0, "steady state fired an XLA compile"

    def test_aot_artifact_roundtrip(self, tmp_path):
        """Cold warm() serializes executables; a second registry against
        the same AOT dir loads them — aot_hit, no trace, no compile —
        and computes bit-identical outputs."""
        import numpy as np

        aot = str(tmp_path / "aot")
        cold = self._registry(aot_dir=aot)
        cold.warm()
        assert all(
            not rep["aot_hit"] for rep in cold.warm_report.values()
        )
        warm = self._registry(aot_dir=aot)
        warm.warm()
        for b, rep in warm.warm_report.items():
            assert rep["aot_hit"], f"bucket {b} missed the AOT artifact"
            assert rep["xla_compiles"] == 0, f"bucket {b} recompiled"
        assert warm.traces == 0, "AOT warm start re-traced"
        x = np.arange(20 * 8, dtype=np.uint32).reshape(20, 8) % 8191
        a = np.asarray(cold.program_for(8)[1](x))
        b = np.asarray(warm.program_for(8)[1](x))
        assert (a == b).all()

    def test_stale_aot_artifact_falls_back_to_compile(self, tmp_path):
        aot = str(tmp_path / "aot")
        cold = self._registry(aot_dir=aot)
        cold.warm()
        for name in os.listdir(aot):
            with open(os.path.join(aot, name), "wb") as fh:
                fh.write(b"corrupt artifact")
        rebuilt = self._registry(aot_dir=aot)
        rebuilt.warm()  # must not raise
        assert all(
            not rep["aot_hit"] for rep in rebuilt.warm_report.values()
        )


# ---------------------------------------------------------------------------
# sidecar end-to-end (host engine over a unix socket)
# ---------------------------------------------------------------------------


class TestSidecar:
    def test_mixed_batch_bit_exact(self, sidecar):
        keys, sigs, digests, expected = mixed_lanes(60)
        provider = SidecarProvider(address=sidecar.address)
        try:
            mask = provider.batch_verify(keys, sigs, digests)
            assert list(mask) == expected
            inproc = SoftwareProvider().batch_verify(keys, sigs, digests)
            assert list(mask) == list(inproc)
            assert not provider.degraded
            assert provider.describe_backend().startswith("serve:")
        finally:
            provider.stop()

    def test_async_pipelined_requests(self, sidecar):
        provider = SidecarProvider(address=sidecar.address)
        try:
            batches = [mixed_lanes(20, seed=s) for s in range(5)]
            resolvers = [
                provider.batch_verify_async(k, s, d)
                for k, s, d, _ in batches
            ]
            for resolver, (_, _, _, expected) in zip(resolvers, batches):
                assert list(resolver()) == expected
        finally:
            provider.stop()

    def test_concurrent_connections(self, sidecar):
        errs = []

        def worker(i):
            provider = SidecarProvider(address=sidecar.address)
            try:
                k, s, d, e = mixed_lanes(15, seed=i)
                if list(provider.batch_verify(k, s, d)) != e:
                    errs.append(i)
            finally:
                provider.stop()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs

    def test_stats_and_ping(self, sidecar):
        provider = SidecarProvider(address=sidecar.address)
        try:
            k, s, d, e = mixed_lanes(10)
            provider.batch_verify(k, s, d)
            assert provider.client.ping()
            stats = provider.client.stats()
            assert stats["engine"] == "host"
            assert stats["stats"]["requests"] >= 1
            assert stats["stats"]["request_latency"]["n"] >= 1
        finally:
            provider.stop()

    def test_tcp_address(self):
        server = SidecarServer(
            "127.0.0.1:0", engine="host", warm_ladder="off"
        )
        server.warm()
        addr = server.start()
        try:
            assert parse_address(addr)[0] == socket.AF_INET
            provider = SidecarProvider(address=addr)
            k, s, d, e = mixed_lanes(12)
            assert list(provider.batch_verify(k, s, d)) == e
            provider.stop()
        finally:
            server.stop()

    def test_garbage_frame_kills_connection_not_server(self, sidecar):
        raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        raw.connect(sidecar.address)
        raw.sendall(b"not a frame at all" * 4)
        raw.close()
        provider = SidecarProvider(address=sidecar.address)
        try:
            k, s, d, e = mixed_lanes(10)
            assert list(provider.batch_verify(k, s, d)) == e
        finally:
            provider.stop()

    def test_malformed_payload_fails_request_not_connection(self, sidecar):
        """A frame whose HEADER parses but whose VERIFY payload does not
        decode is answered ST_ERROR for THAT request id and the stream
        keeps serving: recv_frame consumed the whole length-prefixed
        frame, so the connection is still in sync."""
        client = SidecarClient(sidecar.address)
        try:
            token = client.submit(proto.OP_VERIFY, b"\xff\xff\xff")
            status, _, _, message = proto.decode_verify_response(
                client.await_reply(token)
            )
            assert status == proto.ST_ERROR
            assert "ProtocolError" in message
            # the SAME connection still serves real work
            k, s, d, e = mixed_lanes(10)
            status, _, mask, _ = proto.decode_verify_response(
                client.request(proto.OP_VERIFY, encode_lanes(k, s, d))
            )
            assert status == proto.ST_OK
            assert mask == e
        finally:
            client.close()

    def test_read_loop_stays_responsive_during_slow_verify(self, tmp_path):
        """Verify requests settle on worker threads: while one request
        is stalled in the batcher, the connection's read loop must keep
        draining frames (a PING answers promptly) instead of
        serializing every request behind the slow one."""
        provider = GatedProvider()
        server = SidecarServer(
            str(tmp_path / "slow.sock"), engine="host", provider=provider,
            warm_ladder="off", buckets=(64,), linger_s=0.0,
        )
        server.start()  # no warm(): the gate would stall the warm batch
        client = SidecarClient(server.address)
        try:
            k, s, d, e = mixed_lanes(64, seed=9)
            token = client.submit(proto.OP_VERIFY, encode_lanes(k, s, d))
            assert provider.entered.wait(5.0)
            t0 = time.monotonic()
            assert client.ping()  # same connection, verify still gated
            assert time.monotonic() - t0 < 5.0
            assert not provider.gate.is_set()
            provider.gate.set()
            status, _, mask, _ = proto.decode_verify_response(
                client.await_reply(token)
            )
            assert status == proto.ST_OK
            assert mask == e
        finally:
            provider.gate.set()
            client.close()
            server.stop()


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


class GatedProvider(SoftwareProvider):
    """Computes verdicts eagerly but stalls the batcher's dispatcher on
    a gate, so admitted-but-undispatched lanes accumulate."""

    def __init__(self):
        super().__init__()
        self.gate = threading.Event()
        self.entered = threading.Event()

    def batch_verify_async(self, keys, sigs, digests):
        out = SoftwareProvider.batch_verify(self, keys, sigs, digests)
        self.entered.set()
        self.gate.wait(10.0)
        return lambda: out


class TestAdmissionControl:
    def _squeezed_server(self, tmp_path):
        provider = GatedProvider()
        server = SidecarServer(
            str(tmp_path / "busy.sock"), engine="host", provider=provider,
            warm_ladder="off", buckets=(64,), max_pending_lanes=96,
            linger_s=0.0,
        )
        server.start()  # no warm(): the gate would stall the warm batch
        return server, provider

    def _fill(self, server, provider):
        """Occupy the dispatcher + the lane budget; returns the gated
        requests' resolvers and their expected masks."""
        a = SidecarProvider(address=server.address, sleeper=lambda s: None)
        b = SidecarProvider(address=server.address, sleeper=lambda s: None)
        k1, s1, d1, e1 = mixed_lanes(64, seed=1)
        r1 = a.batch_verify_async(k1, s1, d1)
        assert provider.entered.wait(5.0)
        k2, s2, d2, e2 = mixed_lanes(64, seed=2)
        r2 = b.batch_verify_async(k2, s2, d2)
        deadline = time.monotonic() + 5.0
        while server.batcher.pending_lanes < 64 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.batcher.pending_lanes >= 64
        return (a, b), (r1, e1), (r2, e2)

    def test_full_sidecar_rejects_with_retry_after(self, tmp_path):
        """A raw client sees ST_BUSY + a retry_after hint, never a block
        or an error, while the budget is full; after release the same
        request succeeds."""
        server, provider = self._squeezed_server(tmp_path)
        clients = ()
        try:
            clients, (r1, e1), (r2, e2) = self._fill(server, provider)
            raw = SidecarClient(server.address)
            k3, s3, d3, e3 = mixed_lanes(64, seed=3)
            payload = encode_lanes(k3, s3, d3)
            status, retry_ms, mask, _ = proto.decode_verify_response(
                raw.request(proto.OP_VERIFY, payload)
            )
            assert status == proto.ST_BUSY
            assert retry_ms >= 5
            provider.gate.set()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                status, _, mask, _ = proto.decode_verify_response(
                    raw.request(proto.OP_VERIFY, payload)
                )
                if status == proto.ST_OK:
                    break
                time.sleep(0.02)
            assert status == proto.ST_OK
            assert mask == e3
            assert list(r1()) == e1 and list(r2()) == e2
            raw.close()
        finally:
            provider.gate.set()
            for c in clients:
                c.stop()
            server.stop()

    def test_client_shim_retries_then_degrades(self, tmp_path):
        """The provider's BUSY pacing: bounded retries against a full
        sidecar, then in-process degrade with a bit-exact mask."""
        server, provider = self._squeezed_server(tmp_path)
        clients = ()
        try:
            clients, (r1, e1), (r2, e2) = self._fill(server, provider)
            third = SidecarProvider(
                address=server.address, sleeper=lambda s: None
            )
            k3, s3, d3, e3 = mixed_lanes(64, seed=3)
            mask = third.batch_verify(k3, s3, d3)
            assert third.busy_rejects >= 1
            assert third.degraded  # budget spent against the gated server
            assert list(mask) == e3
            provider.gate.set()
            assert list(r1()) == e1 and list(r2()) == e2
            third.stop()
        finally:
            provider.gate.set()
            for c in clients:
                c.stop()
            server.stop()

    def test_retry_after_scales_with_fill(self, sidecar):
        base = sidecar.retry_after_ms()
        assert base >= 5


class TestTrySubmit:
    def test_try_submit_rejects_when_full_and_recovers(self):
        from fabric_tpu.parallel.batcher import VerifyBatcher

        gate = threading.Event()
        entered = threading.Event()

        class Gated:
            def batch_verify_async(self, keys, sigs, digests):
                entered.set()
                gate.wait(10.0)
                out = [True] * len(keys)
                return lambda: out

        b = VerifyBatcher(Gated(), max_pending_lanes=8, linger_s=0.0)
        try:
            r1 = b.try_submit([object()] * 8, [b"s"] * 8, [b"d"] * 8)
            assert r1 is not None
            assert entered.wait(5.0)
            r2 = b.try_submit([object()] * 8, [b"s"] * 8, [b"d"] * 8)
            deadline = time.monotonic() + 5.0
            while r2 is None and time.monotonic() < deadline:
                # dispatcher may not have taken batch 1 yet; once it has,
                # the budget frees and the retry must admit
                if b.pending_lanes == 0:
                    r2 = b.try_submit(
                        [object()] * 8, [b"s"] * 8, [b"d"] * 8
                    )
                    break
                r3 = b.try_submit([object()] * 8, [b"s"] * 8, [b"d"] * 8)
                assert r3 is None  # full: must reject, never block
                time.sleep(0.01)
            gate.set()
            assert r1() == [True] * 8
            if r2 is not None:
                assert r2() == [True] * 8
        finally:
            gate.set()
            b.stop()


# ---------------------------------------------------------------------------
# degrade ladder (fail-closed, never fail-open)
# ---------------------------------------------------------------------------


class TestDegrade:
    def test_dead_address_degrades_in_process(self, tmp_path):
        provider = SidecarProvider(address=str(tmp_path / "nothing.sock"))
        k, s, d, e = mixed_lanes(20)
        assert list(provider.batch_verify(k, s, d)) == e
        assert provider.degraded
        assert provider.describe_backend().startswith("serve-degraded(")
        provider.stop()

    def test_dial_cooldown_skips_reconnect_spam(self, tmp_path, monkeypatch):
        """After a failed dial the circuit opens: the next batch
        degrades WITHOUT paying another connect attempt (a blackholed
        TCP endpoint would otherwise cost connect_timeout_s per
        batch on the commit path)."""
        provider = SidecarProvider(address=str(tmp_path / "nothing.sock"))
        calls = []
        orig = provider.client._connect

        def counting_connect():
            calls.append(1)
            return orig()

        monkeypatch.setattr(provider.client, "_connect", counting_connect)
        k, s, d, e = mixed_lanes(10)
        try:
            assert list(provider.batch_verify(k, s, d)) == e
            dials = len(calls)
            assert dials >= 1
            assert not provider.client._dial_gate.ready()  # circuit open
            assert list(provider.batch_verify(k, s, d)) == e
            assert len(calls) == dials  # cooling down: no new dial
        finally:
            provider.stop()

    def test_fallback_is_the_probe_ladder(self, tmp_path, monkeypatch):
        """Degrade goes through bccsp.probe_provider() (device if one
        answers, else SW) — an accelerator node whose sidecar dies, or
        whose FABRIC_TPU_SERVE_ADDR went stale, must keep its device
        rather than silently pinning the SW rung."""
        import fabric_tpu.crypto.bccsp as bccsp

        sentinel = SoftwareProvider()
        monkeypatch.setattr(bccsp, "probe_provider", lambda: sentinel)
        provider = SidecarProvider(address=str(tmp_path / "nothing.sock"))
        try:
            assert provider.fallback_provider() is sentinel
        finally:
            provider.stop()

    def test_kill_mid_batch_degrades_bit_exact(self, tmp_path):
        addr = str(tmp_path / "kill.sock")
        gated = GatedProvider()
        server = SidecarServer(
            addr, engine="host", provider=gated, warm_ladder="off",
            buckets=(64,),
        )
        server.start()
        provider = SidecarProvider(address=addr, sleeper=lambda s: None)
        try:
            k, s, d, e = mixed_lanes(30)
            resolver = provider.batch_verify_async(k, s, d)
            assert gated.entered.wait(5.0)  # request is in flight
            server.stop()  # kill with the batch mid-dispatch
            gated.gate.set()
            assert list(resolver()) == e  # re-verified in-process
            assert provider.degraded
        finally:
            gated.gate.set()
            provider.stop()
            server.stop()

    def test_double_fault_fails_closed_all_false(self, tmp_path):
        """Sidecar dead AND the in-process fallback broken: the mask is
        all-False — lanes are never guessed VALID."""

        class BrokenFallback:
            def batch_verify(self, keys, sigs, digests):
                raise RuntimeError("fallback broken too")

        provider = SidecarProvider(
            address=str(tmp_path / "nothing.sock"), fallback=BrokenFallback()
        )
        k, s, d, _ = mixed_lanes(15)
        assert provider.batch_verify(k, s, d) == [False] * 15

    def test_mask_length_skew_is_rejected(self, sidecar, monkeypatch):
        """An OK reply whose mask length disagrees with the request is a
        protocol violation: degrade, never stretch/truncate verdicts."""
        provider = SidecarProvider(address=sidecar.address)
        real_decode = proto.decode_verify_response

        def skewed(payload):
            status, retry, mask, msg = real_decode(payload)
            if status == proto.ST_OK and mask:
                mask = mask[:-1]
            return status, retry, mask, msg

        monkeypatch.setattr(
            "fabric_tpu.serve.client.proto.decode_verify_response", skewed
        )
        k, s, d, e = mixed_lanes(10)
        assert list(provider.batch_verify(k, s, d)) == e
        assert provider.degraded
        provider.stop()

    def test_injected_dispatch_fault_rides_retry(self, sidecar):
        from fabric_tpu.common.faults import FaultPlan, plan_installed

        provider = SidecarProvider(
            address=sidecar.address, sleeper=lambda s: None
        )
        try:
            k, s, d, e = mixed_lanes(25)
            plan = FaultPlan.parse("serve.dispatch=raise:0.5", seed=3)
            with plan_installed(plan):
                for _ in range(4):
                    assert list(provider.batch_verify(k, s, d)) == e
            assert plan.fired().get("serve.dispatch", 0) >= 1
        finally:
            provider.stop()


# ---------------------------------------------------------------------------
# factory rung + env routing
# ---------------------------------------------------------------------------


class TestFactoryRung:
    def test_default_serve_builds_sidecar_provider(self, sidecar):
        from fabric_tpu.crypto.factory import provider_from_config

        provider = provider_from_config(
            {"Default": "SERVE", "SERVE": {"Address": sidecar.address}}
        )
        try:
            assert isinstance(provider, SidecarProvider)
            k, s, d, e = mixed_lanes(10)
            assert list(provider.batch_verify(k, s, d)) == e
        finally:
            provider.stop()

    def test_serve_without_address_is_a_factory_error(self, monkeypatch):
        from fabric_tpu.crypto.factory import FactoryError, provider_from_config

        monkeypatch.delenv("FABRIC_TPU_SERVE_ADDR", raising=False)
        with pytest.raises(FactoryError):
            provider_from_config({"Default": "SERVE"})

    def test_unknown_default_still_errors(self):
        from fabric_tpu.crypto.factory import FactoryError, provider_from_config

        with pytest.raises(FactoryError, match="unknown BCCSP default"):
            provider_from_config({"Default": "NOPE"})

    def test_env_routes_default_provider(self, sidecar, monkeypatch):
        import fabric_tpu.crypto.bccsp as bccsp

        monkeypatch.setenv("FABRIC_TPU_SERVE_ADDR", sidecar.address)
        monkeypatch.setattr(bccsp, "_default", None)
        provider = bccsp.default_provider()
        try:
            assert isinstance(provider, SidecarProvider)
            k, s, d, e = mixed_lanes(10)
            assert list(provider.batch_verify(k, s, d)) == e
        finally:
            provider.stop()
            monkeypatch.setattr(bccsp, "_default", None)

    def test_pipeline_channel_routes_through_sidecar(self, sidecar):
        """peer-plane integration: a provider built from the SERVE rung
        slots into the validator seam like any other provider (the
        Channel/BlockValidator only see the Provider SPI)."""
        from fabric_tpu.crypto.factory import provider_from_config

        provider = provider_from_config(
            {"Default": "SERVE", "SERVE": {"Address": sidecar.address}}
        )
        try:
            k, s, d, e = mixed_lanes(16)
            resolver = provider.batch_verify_async(k, s, d)
            assert list(resolver()) == e
            assert sidecar.stats.summary()["requests"] >= 1
        finally:
            provider.stop()


# ---------------------------------------------------------------------------
# warm restart: sidecar subprocess twice against a persistent cache
# ---------------------------------------------------------------------------


class TestWarmRestart:
    BUCKETS = "8,16"

    def _run_sidecar(self, tmp_path, tag):
        """Start ``python -m fabric_tpu.serve`` with the demo jax
        ladder + a persistent AOT dir, drive one mixed batch through the
        client shim, shut down cleanly.  Returns (warm_report, mask)."""
        addr = str(tmp_path / f"warm-{tag}.sock")
        aot = str(tmp_path / "aot")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "fabric_tpu.serve",
                "--address", addr, "--engine", "host",
                "--warm", "demo", "--buckets", self.BUCKETS,
                "--aot-dir", aot,
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=REPO_ROOT,
        )
        try:
            ready = None
            deadline = time.monotonic() + 240.0
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    break
                if line.startswith("SERVE_READY "):
                    ready = json.loads(line[len("SERVE_READY "):])
                    break
            assert ready is not None, proc.stderr.read()
            provider = SidecarProvider(address=addr)
            keys, sigs, digests, expected = mixed_lanes(20, seed=99)
            mask = provider.batch_verify(keys, sigs, digests)
            assert list(mask) == expected
            assert not provider.degraded
            provider.client.shutdown()
            provider.stop()
            assert proc.wait(timeout=30) == 0
            return ready["warm"], list(mask)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

    def test_second_start_is_aot_warm_with_identical_masks(self, tmp_path):
        """ISSUE acceptance: run the sidecar twice against the same
        persistent cache; the second start must be served entirely from
        the AOT artifacts — zero XLA compiles, zero re-traces — and the
        served masks must be identical."""
        warm1, mask1 = self._run_sidecar(tmp_path, "cold")
        warm2, mask2 = self._run_sidecar(tmp_path, "warm")
        assert mask1 == mask2
        buckets = [b.strip() for b in self.BUCKETS.split(",")]
        for b in buckets:
            rep1 = warm1["per_bucket"][b]
            rep2 = warm2["per_bucket"][b]
            assert not rep1["aot_hit"], f"first start already AOT at {b}"
            assert rep2["aot_hit"], f"second start missed the AOT at {b}"
            assert rep2["xla_compiles"] == 0, f"second start recompiled {b}"
        assert warm2["traces"] == 0, "second start re-traced a program"
        # the wall-clock claim, stated conservatively: the AOT warm start
        # must beat the first start (which paid trace + compile/cache)
        assert warm2["total_warm_ms"] < warm1["total_warm_ms"]
