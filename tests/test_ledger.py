"""Block store + kvledger tests (modeled on core/ledger/kvledger/tests)."""

import hashlib

import pytest

from fabric_tpu.ledger.blockstore import BlockStore
from fabric_tpu.ledger.kvledger import (
    KVLedger,
    deterministic_update_bytes,
    encode_order_preserving_varuint64,
)
from fabric_tpu.ledger.rwset import Version
from fabric_tpu.ledger.statedb import HashedUpdateBatch, UpdateBatch
from fabric_tpu.protos import common_pb2, protoutil


def make_block(number, prev_hash, payloads):
    block = protoutil.new_block(number, prev_hash)
    for p in payloads:
        block.data.data.append(p)
    return protoutil.seal_block(block)


class TestBlockStore:
    def test_append_read_and_chain(self, tmp_path):
        bs = BlockStore(str(tmp_path / "ch.chain"))
        b0 = make_block(0, b"", [b"tx0", b"tx1"])
        bs.add_block(b0)
        b1 = make_block(1, protoutil.block_header_hash(b0.header), [b"tx2"])
        bs.add_block(b1)
        assert bs.height == 2
        assert bs.get_block_by_number(0).data.data[1] == b"tx1"
        assert bs.get_block_by_hash(protoutil.block_header_hash(b1.header)).header.number == 1
        with pytest.raises(ValueError):
            bs.add_block(make_block(5, b"", []))
        with pytest.raises(ValueError):
            bs.add_block(make_block(2, b"wrong-prev-hash", []))

    def test_reopen_rebuilds_index(self, tmp_path):
        path = str(tmp_path / "ch.chain")
        bs = BlockStore(path)
        b0 = make_block(0, b"", [b"a"])
        bs.add_block(b0)
        bs.add_block(make_block(1, protoutil.block_header_hash(b0.header), [b"b"]))
        bs.close()
        bs2 = BlockStore(path)
        assert bs2.height == 2
        assert bs2.get_block_by_number(1).data.data[0] == b"b"

    def test_crash_recovery_truncates_partial_tail(self, tmp_path):
        from fabric_tpu.ledger.blockstore import frame_header

        path = str(tmp_path / "ch.chain")
        bs = BlockStore(path)
        b0 = make_block(0, b"", [b"a"])
        bs.add_block(b0)
        bs.close()
        with open(path, "ab") as f:
            # a torn append: valid header, payload cut off mid-write
            f.write(frame_header(500) + b"partial-write-from-a-crash")
        bs2 = BlockStore(path)
        assert bs2.height == 1
        # and appending still works
        bs2.add_block(make_block(1, protoutil.block_header_hash(b0.header), [b"b"]))
        assert bs2.height == 2


class TestCommitHashBytes:
    def test_order_preserving_varuint(self):
        assert encode_order_preserving_varuint64(0) == b"\x00"
        assert encode_order_preserving_varuint64(1) == b"\x01\x01"
        assert encode_order_preserving_varuint64(256) == b"\x02\x01\x00"
        # ordering property
        vals = [0, 1, 2, 255, 256, 1 << 40, (1 << 64) - 1]
        encs = [encode_order_preserving_varuint64(v) for v in vals]
        assert encs == sorted(encs)

    def test_deterministic_update_bytes_stable(self):
        u1, h1 = UpdateBatch(), HashedUpdateBatch()
        u2, h2 = UpdateBatch(), HashedUpdateBatch()
        v = Version(3, 1)
        # insert in different orders
        for batch in (u1, u2):
            pass
        u1.put("ns2", "k1", b"a", v)
        u1.put("ns1", "kz", b"b", v)
        u1.delete("ns1", "ka", v)
        u2.delete("ns1", "ka", v)
        u2.put("ns1", "kz", b"b", v)
        u2.put("ns2", "k1", b"a", v)
        h1.put("ns1", "collB", b"\x01", b"\xaa", v)
        h2.put("ns1", "collB", b"\x01", b"\xaa", v)
        assert deterministic_update_bytes(u1, h1) == deterministic_update_bytes(u2, h2)
        # empty namespace (channel config) is excluded
        u1.put("", "resourcesconfigtx.CHANNEL_CONFIG_KEY", b"cfg", v)
        assert deterministic_update_bytes(u1, h1) == deterministic_update_bytes(u2, h2)
