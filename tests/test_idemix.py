"""Idemix suite tests (mirror of reference idemix/idemix_test.go):
curve/pairing sanity, issuer keys, credential issuance, signature
roundtrips with selective disclosure, nym signatures, weak-BB, CRI."""

import random

import pytest

from conftest import requires_crypto

from fabric_tpu.crypto import fp256bn as bn
from fabric_tpu import idemix
from fabric_tpu.protos import idemix_pb2

RNG = random.Random(42)


# ---------------------------------------------------------------------------
# curve-level sanity
# ---------------------------------------------------------------------------


def test_curve_parameters():
    u = bn.U
    assert bn.P == 36 * u**4 + 36 * u**3 + 24 * u**2 + 6 * u + 1
    assert bn.R == 36 * u**4 + 36 * u**3 + 18 * u**2 + 6 * u + 1
    assert bn.g1_is_on_curve(bn.G1_GEN)
    assert bn.g2_is_on_curve(bn.G2_GEN)
    assert bn.g1_mul(bn.G1_GEN, bn.R) is None
    assert bn.g2_mul(bn.G2_GEN, bn.R) is None


def test_pairing_bilinear():
    a = RNG.randrange(bn.R)
    b = RNG.randrange(bn.R)
    gt = bn.pairing(bn.G2_GEN, bn.G1_GEN)
    assert gt != bn.FP12_ONE
    assert bn.fp12_pow(gt, bn.R) == bn.FP12_ONE
    lhs = bn.pairing(bn.g2_mul(bn.G2_GEN, a), bn.g1_mul(bn.G1_GEN, b))
    assert lhs == bn.fp12_pow(gt, a * b % bn.R)


def test_serialization_roundtrip():
    p1 = bn.g1_mul(bn.G1_GEN, RNG.randrange(bn.R))
    assert bn.g1_from_bytes(bn.g1_to_bytes(p1)) == p1
    assert len(bn.g1_to_bytes(p1)) == 65
    p2 = bn.g2_mul(bn.G2_GEN, RNG.randrange(bn.R))
    assert bn.g2_from_bytes(bn.g2_to_bytes(p2)) == p2
    assert len(bn.g2_to_bytes(p2)) == 128


# ---------------------------------------------------------------------------
# scheme fixtures
# ---------------------------------------------------------------------------

ATTRS = ["Attr1", "Attr2", "Attr3", "Attr4", "Attr5"]
ATTR_VALUES = [1, 2, 3, 4, 5]
RH_INDEX = 4


@pytest.fixture(scope="module")
def issuer_key():
    return idemix.new_issuer_key(ATTRS, RNG)


@pytest.fixture(scope="module")
def user(issuer_key):
    sk = bn.rand_mod_order(RNG)
    nonce = bn.big_to_bytes(bn.rand_mod_order(RNG))
    req = idemix.new_cred_request(sk, nonce, issuer_key.ipk, RNG)
    cred = idemix.new_credential(issuer_key, req, ATTR_VALUES, RNG)
    return sk, cred


@pytest.fixture(scope="module")
def rev_key():
    return idemix.generate_long_term_revocation_key()


@pytest.fixture(scope="module")
def cri(rev_key):
    return idemix.create_cri(rev_key, [], 0, idemix.ALG_NO_REVOCATION, RNG)


def test_issuer_key_proof(issuer_key):
    idemix.check_issuer_public_key(issuer_key.ipk)
    # tampered W fails the PoK
    bad = idemix_pb2.IssuerPublicKey()
    bad.CopyFrom(issuer_key.ipk)
    bad.w.CopyFrom(
        idemix.ecp2_to_proto(bn.g2_mul(bn.G2_GEN, 123))
    )
    with pytest.raises(idemix.IdemixError):
        idemix.check_issuer_public_key(bad)


def test_duplicate_attributes_rejected():
    with pytest.raises(idemix.IdemixError):
        idemix.new_issuer_key(["a", "a"], RNG)


def test_cred_request_verifies(issuer_key):
    sk = bn.rand_mod_order(RNG)
    nonce = bn.big_to_bytes(bn.rand_mod_order(RNG))
    req = idemix.new_cred_request(sk, nonce, issuer_key.ipk, RNG)
    idemix.verify_cred_request(req, issuer_key.ipk)
    req.proof_s = bn.big_to_bytes(bn.big_from_bytes(req.proof_s) ^ 1)
    with pytest.raises(idemix.IdemixError):
        idemix.verify_cred_request(req, issuer_key.ipk)


def test_credential_verifies(issuer_key, user):
    sk, cred = user
    idemix.verify_credential(cred, sk, issuer_key.ipk)


def test_credential_wrong_sk_fails(issuer_key, user):
    _, cred = user
    with pytest.raises(idemix.IdemixError):
        idemix.verify_credential(cred, 12345, issuer_key.ipk)


def test_credential_tampered_attr_fails(issuer_key, user):
    sk, cred = user
    bad = idemix_pb2.Credential()
    bad.CopyFrom(cred)
    bad.attrs[0] = bn.big_to_bytes(999)
    with pytest.raises(idemix.IdemixError):
        idemix.verify_credential(bad, sk, issuer_key.ipk)


@requires_crypto
def test_signature_roundtrip_no_disclosure(issuer_key, user, cri):
    sk, cred = user
    nym, r_nym = idemix.make_nym(sk, issuer_key.ipk, RNG)
    disclosure = [0, 0, 0, 0, 0]
    msg = b"some message"
    sig = idemix.new_signature(
        cred, sk, nym, r_nym, issuer_key.ipk, disclosure, msg,
        RH_INDEX, cri, RNG,
    )
    idemix.verify_signature(
        sig, disclosure, issuer_key.ipk, msg,
        [None] * 5, RH_INDEX, None, 0,
    )


@requires_crypto
def test_signature_roundtrip_selective_disclosure(issuer_key, user, cri):
    sk, cred = user
    nym, r_nym = idemix.make_nym(sk, issuer_key.ipk, RNG)
    disclosure = [0, 1, 1, 0, 0]  # disclose attrs 1 and 2
    msg = b"some message"
    sig = idemix.new_signature(
        cred, sk, nym, r_nym, issuer_key.ipk, disclosure, msg,
        RH_INDEX, cri, RNG,
    )
    attr_values = [None, ATTR_VALUES[1], ATTR_VALUES[2], None, None]
    idemix.verify_signature(
        sig, disclosure, issuer_key.ipk, msg,
        attr_values, RH_INDEX, None, 0,
    )
    # wrong disclosed value -> invalid
    with pytest.raises(idemix.IdemixError):
        idemix.verify_signature(
            sig, disclosure, issuer_key.ipk, msg,
            [None, 999, ATTR_VALUES[2], None, None], RH_INDEX, None, 0,
        )


@requires_crypto
def test_signature_wrong_message_fails(issuer_key, user, cri):
    sk, cred = user
    nym, r_nym = idemix.make_nym(sk, issuer_key.ipk, RNG)
    disclosure = [0, 0, 0, 0, 0]
    sig = idemix.new_signature(
        cred, sk, nym, r_nym, issuer_key.ipk, disclosure, b"msg",
        RH_INDEX, cri, RNG,
    )
    with pytest.raises(idemix.IdemixError):
        idemix.verify_signature(
            sig, disclosure, issuer_key.ipk, b"other msg",
            [None] * 5, RH_INDEX, None, 0,
        )


@requires_crypto
def test_signature_tampered_aprime_fails(issuer_key, user, cri):
    sk, cred = user
    nym, r_nym = idemix.make_nym(sk, issuer_key.ipk, RNG)
    disclosure = [0, 0, 0, 0, 0]
    sig = idemix.new_signature(
        cred, sk, nym, r_nym, issuer_key.ipk, disclosure, b"msg",
        RH_INDEX, cri, RNG,
    )
    sig.a_prime.CopyFrom(
        idemix.ecp_to_proto(bn.g1_mul(bn.G1_GEN, 7))
    )
    with pytest.raises(idemix.IdemixError):
        idemix.verify_signature(
            sig, disclosure, issuer_key.ipk, b"msg",
            [None] * 5, RH_INDEX, None, 0,
        )


def test_nym_signature_roundtrip(issuer_key, user):
    sk, _ = user
    nym, r_nym = idemix.make_nym(sk, issuer_key.ipk, RNG)
    sig = idemix.new_nym_signature(
        sk, nym, r_nym, issuer_key.ipk, b"testing", RNG
    )
    idemix.verify_nym_signature(sig, nym, issuer_key.ipk, b"testing")
    with pytest.raises(idemix.IdemixError):
        idemix.verify_nym_signature(sig, nym, issuer_key.ipk, b"wrong")


def test_wbb_roundtrip():
    sk, pk = idemix.wbb_keygen(RNG)
    m = bn.rand_mod_order(RNG)
    sig = idemix.wbb_sign(sk, m)
    idemix.wbb_verify(pk, sig, m)
    with pytest.raises(idemix.IdemixError):
        idemix.wbb_verify(pk, sig, (m + 1) % bn.R)


@requires_crypto
def test_cri_epoch_pk(rev_key, cri):
    idemix.verify_epoch_pk(
        rev_key.public_key(), cri.epoch_pk, cri.epoch_pk_sig, 0,
        idemix.ALG_NO_REVOCATION,
    )
    with pytest.raises(idemix.IdemixError):
        idemix.verify_epoch_pk(
            rev_key.public_key(), cri.epoch_pk, cri.epoch_pk_sig, 1,
            idemix.ALG_NO_REVOCATION,
        )
