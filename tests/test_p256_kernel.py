"""Differential tests: batched device P-256 kernel vs the pure-Python oracle."""

import hashlib
import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from fabric_tpu.crypto import p256
from fabric_tpu.ops import bignum as bn
from fabric_tpu.ops import p256_kernel as pk

R = 1 << bn.RADIX_BITS


def to_mont_int(x):
    return (x * R) % p256.P


def make_point_batch(pts):
    """affine pts (or None) -> packed (3, 20, B) Montgomery projective."""
    xs, ys, zs = [], [], []
    for pt in pts:
        if pt is None:
            xs.append(0)
            ys.append(to_mont_int(1))
            zs.append(0)
        else:
            xs.append(to_mont_int(pt[0]))
            ys.append(to_mont_int(pt[1]))
            zs.append(to_mont_int(1))
    return pk.Point(
        pk.fe(jnp.asarray(bn.ints_to_limbs(xs))),
        pk.fe(jnp.asarray(bn.ints_to_limbs(ys))),
        pk.fe(jnp.asarray(bn.ints_to_limbs(zs))),
    )


def read_affine(point):
    """device projective Montgomery -> list of affine pts / None."""
    xs = bn.limbs_to_ints(np.asarray(bn.from_mont(pk.CTX_P, bn.restack(point.x.limbs))))
    ys = bn.limbs_to_ints(np.asarray(bn.from_mont(pk.CTX_P, bn.restack(point.y.limbs))))
    zs = bn.limbs_to_ints(np.asarray(bn.from_mont(pk.CTX_P, bn.restack(point.z.limbs))))
    out = []
    for x, y, z in zip(xs, ys, zs):
        if z == 0:
            out.append(None)
        else:
            zi = pow(z, -1, p256.P)
            out.append(((x * zi) % p256.P, (y * zi) % p256.P))
    return out


class TestPointOps:
    def test_add_random_and_special_cases(self):
        kps = [p256.generate_keypair() for _ in range(3)]
        g = p256.GENERATOR
        p_list = [kps[0].pub, kps[1].pub, g, g, None, kps[2].pub, None]
        q_list = [
            kps[1].pub,
            kps[1].pub,  # doubling via add
            p256.point_neg(g),  # P + (-P) = infinity
            None,  # P + 0
            g,  # 0 + P
            kps[2].pub,  # doubling again
            None,  # 0 + 0
        ]
        got = read_affine(pk.point_add(make_point_batch(p_list), make_point_batch(q_list)))
        want = [p256.point_add(a, b) for a, b in zip(p_list, q_list)]
        assert got == want

    def test_double(self):
        kps = [p256.generate_keypair().pub for _ in range(4)]
        pts = kps + [p256.GENERATOR, None]
        got = read_affine(pk.point_double(make_point_batch(pts)))
        want = [p256.point_add(a, a) for a in pts]
        assert got == want


class TestGTable:
    def test_rows_match_oracle(self):
        tab = pk.g_small_table()
        rinv = pow(R, -1, p256.P)
        for d in range(16):
            x = (bn.limbs_to_int(tab[d, 0]) * rinv) % p256.P
            y = (bn.limbs_to_int(tab[d, 1]) * rinv) % p256.P
            z = (bn.limbs_to_int(tab[d, 2]) * rinv) % p256.P
            want = p256.scalar_mult(d, p256.GENERATOR)
            if want is None:
                assert z == 0
            else:
                assert z == 1 and (x, y) == want


def run_verify(cases, lanes=16):
    """cases: list of (pub, digest, r, s, precheck_ok). Pads every call to
    one batch shape so the jitted kernel compiles exactly once per test
    session."""
    n = len(cases)
    assert n <= lanes
    pad = [(p256.GENERATOR, b"\x00" * 32, 1, 1, False)] * (lanes - n)
    cases = list(cases) + pad
    e = bn.ints_to_limbs([p256.hash_to_int(d) for _, d, _, _, _ in cases])
    r = bn.ints_to_limbs([c[2] % (1 << 256) for c in cases])
    s = bn.ints_to_limbs([c[3] % (1 << 256) for c in cases])
    qx = bn.ints_to_limbs([c[0][0] for c in cases])
    qy = bn.ints_to_limbs([c[0][1] for c in cases])
    ok = jnp.asarray([c[4] for c in cases], dtype=bool)
    out = pk.verify_batch_jit(
        jnp.asarray(e), jnp.asarray(r), jnp.asarray(s), jnp.asarray(qx), jnp.asarray(qy), ok
    )
    return list(np.asarray(out))[:n]


class TestVerifyBatch:
    # ~60s warm in isolation / ~180s inside the full suite, all
    # execution (NOTES_BUILD tier-1 budget forensics) — slow-marked;
    # the small-batch tests below keep kernel-vs-oracle parity on the
    # SAME compiled program in tier-1.
    @pytest.mark.slow
    def test_differential_vs_oracle(self):
        cases = []
        expect = []
        for i in range(12):
            kp = p256.generate_keypair()
            digest = hashlib.sha256(f"tx {i}".encode()).digest()
            r, s = p256.sign_digest(kp.priv, digest)
            kind = i % 4
            if kind == 0:  # valid
                cases.append((kp.pub, digest, r, s, True))
                expect.append(True)
            elif kind == 1:  # wrong digest
                cases.append((kp.pub, hashlib.sha256(b"no").digest(), r, s, True))
                expect.append(False)
            elif kind == 2:  # tampered s
                s2 = (s + 1) % p256.N or 1
                cases.append((kp.pub, digest, r, s2, True))
                expect.append(p256.verify_digest(kp.pub, digest, r, s2))
            else:  # wrong key
                other = p256.generate_keypair()
                cases.append((other.pub, digest, r, s, True))
                expect.append(False)
        got = run_verify(cases)
        assert got == expect
        # cross-check the oracle agrees on every case
        for (pub, digest, r, s, pre), g in zip(cases, got):
            assert p256.verify_digest(pub, digest, r, s) == g

    def test_precheck_mask_gates_result(self):
        kp = p256.generate_keypair()
        digest = hashlib.sha256(b"masked").digest()
        r, s = p256.sign_digest(kp.priv, digest)
        got = run_verify([(kp.pub, digest, r, s, False), (kp.pub, digest, r, s, True)])
        assert got == [False, True]

    def test_edge_scalars(self):
        """e = 0 digest; u1 = 0 path and tiny r/s values."""
        kp = p256.generate_keypair()
        zero_digest = b"\x00" * 32
        r, s = p256.sign_digest(kp.priv, zero_digest)
        cases = [
            (kp.pub, zero_digest, r, s, True),
            (kp.pub, zero_digest, 1, 1, True),
            (kp.pub, zero_digest, p256.N - 1, p256.HALF_N, True),
        ]
        got = run_verify(cases)
        want = [p256.verify_digest(pub, d, rr, ss) for pub, d, rr, ss, _ in cases]
        assert got == want
        assert got[0] is np.True_ or got[0] == True  # noqa: E712

    def test_fixed_nonce_vectors(self):
        """Deterministic vectors with chosen nonces (repeatable regression)."""
        priv = 0xC9AFA9D845BA75166B5C215767B1D6934E50C3DB36E89B127B8A622B120F6721
        pub = p256.scalar_mult(priv, p256.GENERATOR)
        digest = hashlib.sha256(b"sample").digest()
        r, s = p256.sign_digest(priv, digest, k=0xA6E3C57DD01ABE90086538398355DD4C3B17AA873382B0F24D6129493D8AAD60)
        assert run_verify([(pub, digest, r, s, True)]) == [True]


class TestVariants:
    """The TPU default (microcond) and the micro fallback must match the
    oracle too — CI otherwise only exercises the CPU-default inline
    path while the device runs a different trace."""

    @pytest.mark.slow  # each variant is its own re-traced program:
    # real minutes cold / tens of seconds warm on the gate box
    @pytest.mark.parametrize("variant", ["microcond", "micro"])
    def test_variant_differential(self, variant, monkeypatch):
        monkeypatch.setenv("FABRIC_TPU_KERNEL_VARIANT", variant)
        import jax

        fresh_jit = jax.jit(pk.verify_batch_device)  # re-trace with the env var
        cases = []
        for i in range(16):
            kp = p256.generate_keypair()
            digest = hashlib.sha256(f"variant {i}".encode()).digest()
            r, s = p256.sign_digest(kp.priv, digest)
            if i % 4 == 1:
                digest = hashlib.sha256(b"wrong").digest()
            if i % 4 == 2:
                s = (s + 1) % p256.N or 1
            cases.append((kp.pub, digest, r, s))
        e = bn.ints_to_limbs([p256.hash_to_int(d) for _, d, _, _ in cases])
        r_l = bn.ints_to_limbs([c[2] for c in cases])
        s_l = bn.ints_to_limbs([c[3] for c in cases])
        qx = bn.ints_to_limbs([c[0][0] for c in cases])
        qy = bn.ints_to_limbs([c[0][1] for c in cases])
        ok = jnp.ones((16,), dtype=bool)
        got = list(np.asarray(fresh_jit(
            jnp.asarray(e), jnp.asarray(r_l), jnp.asarray(s_l),
            jnp.asarray(qx), jnp.asarray(qy), ok,
        )))
        want = [p256.verify_digest(c[0], c[1], c[2], c[3]) for c in cases]
        assert got == want
