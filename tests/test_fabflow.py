"""fabflow: interval-domain unit tests, one firing fixture + negative
control per rule, suppression semantics, CLI plumbing, and the repo
self-check (the CI gate invariant: ``fabflow fabric_tpu/`` reports 0
unsuppressed findings and every suppression reason states a computed
bound)."""

import json
import re
import textwrap
from pathlib import Path

import pytest

from fabric_tpu.tools import fabflow
from fabric_tpu.tools.fabflow import Interval

REPO_ROOT = Path(__file__).resolve().parent.parent


def flow(src: str, path: str = "fabric_tpu/ops/fixture.py", rules=None):
    findings, _ = fabflow.analyze_source(textwrap.dedent(src), path, rules)
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# interval domain
# ---------------------------------------------------------------------------


def test_interval_add_mul_widen_exactly():
    a = Interval(0, fabflow.LIMB_MASK)
    assert a.add(a) == Interval(0, 2 * fabflow.LIMB_MASK)
    # products of canonical limbs stay under 2^26: the CIOS premise
    assert a.mul(a) == Interval(0, fabflow.LIMB_MASK ** 2)
    assert a.mul(a).hi < 1 << 26


def test_interval_lshift_widens_and_mask_clamps():
    a = Interval(0, fabflow.LIMB_MASK)
    assert a.lshift(Interval(13, 13)) == Interval(0, fabflow.LIMB_MASK << 13)
    # & LIMB_MASK clamps anything — including negative int32 borrows
    wide = Interval(-(1 << 31), (1 << 31) - 1)
    assert wide.and_(Interval(fabflow.LIMB_MASK, fabflow.LIMB_MASK)) == (
        Interval(0, fabflow.LIMB_MASK)
    )


def test_interval_rshift_carry_bound():
    acc = Interval(0, 20 << 27)
    assert acc.rshift(Interval(13, 13)).hi == (20 << 27) >> 13


def test_interval_widen_terminates_on_thresholds():
    cur = Interval(0, 1)
    for _ in range(64):
        nxt = cur.widen(cur.add(Interval(1, 1)))
        if nxt == cur:
            break
        cur = nxt
    else:
        pytest.fail("widening did not reach a fixpoint")
    assert cur.hi is None  # topped out, not oscillating


def test_widening_loop_terminates_in_analysis():
    # unknown-trip loop accumulating into a uint32 lane: the fixpoint
    # must terminate (widening) AND report the overflow it widens into
    findings = flow(
        """
        import numpy as np
        def count(a, flags):
            t = a
            while flags.any():
                t = t + np.uint32(1)
            return t
        """
    )
    assert "limb-overflow" in rule_ids(findings)


# ---------------------------------------------------------------------------
# limb-overflow
# ---------------------------------------------------------------------------


def test_limb_overflow_fires_on_deep_accumulation():
    # 71 products of canonical limbs: 71 * 8191^2 > 2^32
    findings = flow(
        """
        def acc(a, b):
            t = a * b
            for _ in range(70):
                t = t + a * b
            return t
        """,
        rules=["limb-overflow"],
    )
    assert rule_ids(findings) == ["limb-overflow"]
    assert "exceeds uint32" in findings[0].message


def test_limb_overflow_negative_control_headroom_holds():
    # 31 products stay far below 2^32 — the lazy-carry discipline
    findings = flow(
        """
        def acc(a, b):
            t = a * b
            for _ in range(30):
                t = t + a * b
            return t
        """,
        rules=["limb-overflow"],
    )
    assert findings == []


def test_limb_overflow_cios_proof_sensitivity():
    # the real recurrence at radix 2^13 passes (see the repo self-check);
    # widening the per-iteration term past the headroom must fire
    # per-iteration terms: a*b <= 8191^2 ~ 2^26, a*2^14 ~ 2^27; three of
    # them over 20 iterations is ~6.7e9 > 2^32 — one fewer is ~4.03e9,
    # inside the container (the same margin the real CIOS loop lives on)
    src = """
        import jax.numpy as jnp

        def cios_like(a, b):
            t = jnp.zeros_like(a)
            for i in range(20):
                t = t + a * b + a * jnp.uint32(1 << 14) + a * jnp.uint32(1 << 14)
            return t
        """
    assert rule_ids(flow(src, rules=["limb-overflow"])) == ["limb-overflow"]
    ok = """
        import jax.numpy as jnp

        def cios_like(a, b):
            t = jnp.zeros_like(a)
            for i in range(20):
                t = t + a * b + a * jnp.uint32(1 << 14)
            return t
        """
    assert flow(ok, rules=["limb-overflow"]) == []


def test_limb_overflow_int32_borrow_is_clean():
    # the cond_sub idiom: int32 reinterpretation + borrow stays in range
    findings = flow(
        """
        import jax.numpy as jnp

        def cond_sub(x, m):
            d = x.astype(jnp.int32) - m.astype(jnp.int32)
            return d >> 13
        """,
        rules=["limb-overflow", "dtype-narrowing"],
    )
    assert findings == []


def test_host_python_ints_never_flagged():
    # host big-int files work in Python ints: no container, no overflow
    findings = flow(
        """
        P = 2**256 - 189

        def mul(a: int, b: int) -> int:
            return (a * b * a * b) % P
        """,
        path="fabric_tpu/common/p256.py",
        rules=["limb-overflow"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# dtype-narrowing
# ---------------------------------------------------------------------------


def test_dtype_narrowing_fires_on_truncating_astype():
    findings = flow(
        """
        import jax.numpy as jnp

        def f(a, b):
            return (a + b).astype(jnp.uint8)
        """,
        rules=["dtype-narrowing"],
    )
    assert rule_ids(findings) == ["dtype-narrowing"]


def test_dtype_narrowing_negative_control_masked_first():
    findings = flow(
        """
        import jax.numpy as jnp
        import numpy as np

        def f(a):
            return (a & np.uint32(255)).astype(jnp.uint8)
        """,
        rules=["dtype-narrowing"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# float-contamination
# ---------------------------------------------------------------------------


def test_float_contamination_fires_on_float_operand_and_div():
    assert rule_ids(
        flow("def f(a):\n    return a * 1.5\n",
             rules=["float-contamination"])
    ) == ["float-contamination"]
    assert rule_ids(
        flow("def f(a, b):\n    return a / b\n",
             rules=["float-contamination"])
    ) == ["float-contamination"]


def test_float_contamination_negative_control():
    findings = flow(
        "def f(a, b):\n    return (a * 2) >> 1\n",
        rules=["float-contamination"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# const-drift
# ---------------------------------------------------------------------------


def test_const_drift_fires_on_hardcoded_limb_constants():
    findings = flow(
        """
        def f(x):
            return (x >> 13) & 8191
        """,
        rules=["const-drift"],
    )
    assert set(rule_ids(findings)) == {"const-drift"}
    assert any("LIMB_BITS" in f.message for f in findings)
    assert any("LIMB_MASK" in f.message for f in findings)


def test_const_drift_range_and_pow_forms():
    findings = flow(
        """
        def g(xs):
            out = 0
            for i in range(20):
                out += xs[i] % (2 ** 13)
            return out
        """,
        rules=["const-drift"],
    )
    assert "const-drift" in rule_ids(findings)


def test_const_drift_negative_control_imported_names():
    findings = flow(
        """
        from fabric_tpu.ops.bignum import LIMB_BITS, LIMB_MASK, NLIMBS

        def f(x):
            return (x >> LIMB_BITS) & LIMB_MASK

        def g(table):
            return table[13] + table[20]  # data indices, not limb math
        """,
        rules=["const-drift"],
    )
    assert findings == []


def test_const_drift_only_in_limb_tier():
    findings = flow(
        "def f(x):\n    return x >> 13\n",
        path="fabric_tpu/gossip/fixture.py",
        rules=["const-drift"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# mask-fail-open
# ---------------------------------------------------------------------------

MASK_PATH = "fabric_tpu/validation/fixture.py"


def test_mask_fail_open_fires_on_swallowing_handler():
    findings = flow(
        """
        from fabric_tpu.common.txflags import TxValidationCode

        def parse(tx, data):
            try:
                tx.code = decode(data)
            except ValueError:
                pass
        """,
        path=MASK_PATH,
        rules=["mask-fail-open"],
    )
    assert rule_ids(findings) == ["mask-fail-open"]


def test_mask_fail_open_fires_on_valid_in_handler():
    findings = flow(
        """
        from fabric_tpu.common.txflags import TxValidationCode

        def assemble(flags, i, data):
            try:
                check(data)
            except ValueError:
                flags.set_flag(i, TxValidationCode.VALID)
        """,
        path=MASK_PATH,
        rules=["mask-fail-open"],
    )
    assert rule_ids(findings) == ["mask-fail-open"]
    assert "VALID" in findings[0].message


def test_mask_fail_open_fires_on_early_valid_return():
    findings = flow(
        """
        from fabric_tpu.common.txflags import TxValidationCode

        def classify(tx):
            if tx.fast_path:
                return TxValidationCode.VALID
            return compute_code(tx)
        """,
        path=MASK_PATH,
        rules=["mask-fail-open"],
    )
    assert rule_ids(findings) == ["mask-fail-open"]


def test_mask_fail_open_negative_controls():
    # INVALID-family assignment, raise, delegation, exception handoff,
    # and the narrow-typed retry idiom are all fail-closed
    src = """
        import queue
        from fabric_tpu.common.txflags import TxValidationCode

        def parse(tx, data):
            try:
                tx.code = decode(data)
            except ValueError:
                tx.code = TxValidationCode.BAD_PAYLOAD

        def assemble(flags, i, data):
            try:
                check(data)
            except ValueError as e:
                raise RuntimeError("abort block") from e

        def resolve(flags, q, on_error, block, exc=None):
            while True:
                try:
                    item = q.get(timeout=0.2)
                except queue.Empty:
                    continue
                try:
                    flags = commit(item)
                except Exception as exc:
                    on_error(block, exc)

        def fallback(tx, data):
            try:
                tx.code = decode(data)
            except ValueError:
                out = host_decode(data)
                return out
        """
    assert flow(src, path=MASK_PATH, rules=["mask-fail-open"]) == []


def test_mask_fail_open_is_path_sensitive_on_guarded_delegation():
    # the pipeline's pre-fix silent-drop shape: the error callback only
    # runs under `if cb is not None:` with no else — the empty branch
    # swallows the exception, so the handler must FIRE
    guarded = """
        def _commit_loop(self):
            while True:
                block, prepared = self._prepared.get()
                try:
                    flags = self.channel.store_block(block, prepared=prepared)
                except Exception as exc:
                    if self.on_error is not None:
                        self.on_error(block, exc)
        """
    findings = flow(
        guarded, path="fabric_tpu/parallel/fixture.py",
        rules=["mask-fail-open"],
    )
    assert rule_ids(findings) == ["mask-fail-open"]
    # the post-fix shape — BOTH branches hand the exception onward —
    # is fail-closed
    closed = guarded.rstrip() + (
        "\n                    else:"
        "\n                        log.error('commit failed: %s', exc)\n"
    )
    assert flow(
        closed, path="fabric_tpu/parallel/fixture.py",
        rules=["mask-fail-open"],
    ) == []


def test_tool_constants_match_canonical_limbparams():
    # fabflow never imports analyzed code at gate time, so it carries
    # its own copies of the limb constants; this pins them to the
    # canonical source so the proof can never silently describe a
    # different radix than the kernels run
    from fabric_tpu.common import limbparams

    assert fabflow.LIMB_BITS == limbparams.LIMB_BITS
    assert fabflow.NLIMBS == limbparams.NLIMBS
    assert fabflow.LIMB_MASK == limbparams.LIMB_MASK
    assert fabflow.RADIX_BITS == limbparams.RADIX_BITS


def test_mask_fail_open_ignores_non_flag_functions():
    findings = flow(
        """
        def probe(registry, name):
            try:
                return registry.get(name)
            except KeyError:
                pass
        """,
        path=MASK_PATH,
        rules=["mask-fail-open"],
    )
    assert findings == []


def test_mask_fail_open_only_in_mask_tier():
    findings = flow(
        """
        from fabric_tpu.common.txflags import TxValidationCode

        def parse(tx, data):
            try:
                tx.code = decode(data)
            except ValueError:
                pass
        """,
        path="fabric_tpu/gossip/fixture.py",
        rules=["mask-fail-open"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# suppression semantics
# ---------------------------------------------------------------------------


def test_suppression_silences_named_rule_and_counts():
    src = (
        "def f(x):\n"
        "    return x >> 13  # fabflow: disable=const-drift  "
        "# shift is the wire format's 13, bound [0, 8191]\n"
    )
    findings, suppressed = fabflow.analyze_source(
        src, "fabric_tpu/ops/fixture.py", ["const-drift"]
    )
    assert findings == []
    assert suppressed == 1


def test_suppression_other_rule_does_not_silence():
    src = (
        "def f(x):\n"
        "    return x >> 13  # fabflow: disable=limb-overflow  # wrong id\n"
    )
    findings, suppressed = fabflow.analyze_source(
        src, "fabric_tpu/ops/fixture.py", ["const-drift"]
    )
    assert rule_ids(findings) == ["const-drift"]
    assert suppressed == 0


def test_suppression_reason_is_parsed():
    sup = fabflow.parse_suppressions(
        "x = 1  # fabflow: disable=limb-overflow  # bound [0, 2**27]\n"
    )
    assert sup[1][0] == {"limb-overflow"}
    assert "2**27" in sup[1][1]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_json_output(tmp_path, capsys):
    f = tmp_path / "fabric_tpu" / "ops" / "fixture.py"
    f.parent.mkdir(parents=True)
    f.write_text("def f(x):\n    return x >> 13\n")
    rc = fabflow.main(["--json", str(f)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    assert [x["rule"] for x in out["findings"]] == ["const-drift"]


def test_cli_list_rules_and_bad_rule(capsys):
    assert fabflow.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in fabflow.RULES:
        assert rid in out
    assert fabflow.main(["--rules", "bogus", "x.py"]) == 2


def test_cli_missing_path(capsys):
    assert fabflow.main(["/nonexistent/nope.py"]) == 2


# ---------------------------------------------------------------------------
# the repo self-check: the gate invariant
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def repo_findings():
    return fabflow.analyze_paths([str(REPO_ROOT / "fabric_tpu")])


def test_repo_is_clean(repo_findings):
    findings, stats = repo_findings
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in findings
    )


def test_toolkit_port_changed_nothing(repo_findings):
    """The PR 11 toolkit extraction is behavior-pinned: same chassis
    objects, same rule ids, and the repo's suppressed count exactly as
    before the port (the one qm_term relational-underflow bet)."""
    from fabric_tpu.tools import toolkit

    assert fabflow.Finding is toolkit.Finding
    assert fabflow.DEFAULT_EXCLUDES == toolkit.DEFAULT_EXCLUDES
    assert sorted(fabflow.RULES) == [
        "const-drift", "dtype-narrowing", "float-contamination",
        "limb-overflow", "mask-fail-open",
    ]
    _findings, stats = repo_findings
    assert stats["suppressed"] == 1
    collected = []
    fabflow.analyze_sources(
        {
            "fabric_tpu/ops/fixture.py": (
                "import numpy as np\n"
                "def f(x):\n"
                "    acc = np.uint64(2**63) + np.uint64(2**63)"
                "  # fabflow: disable=limb-overflow  # fixture: 2**64\n"
                "    return acc\n"
            )
        },
        ["limb-overflow"],
        collected,
    )
    assert [f.rule for f in collected] == ["limb-overflow"]


def test_repo_suppressions_state_computed_bounds(repo_findings):
    _, stats = repo_findings
    reasons = fabflow.suppression_reasons([str(REPO_ROOT / "fabric_tpu")])
    assert len(reasons) >= 1  # the qm_term relational-underflow bet
    for path, line, rules, reason in reasons:
        assert reason, f"{path}:{line}: suppression without a reason"
        assert re.search(r"\d", reason), (
            f"{path}:{line}: suppression reason must state the computed "
            f"worst-case bound: {reason!r}"
        )


def test_limb_overflow_bn_pair_kernel_sensitivity():
    """PR 7 (hostbn) sensitivity fixture: the pair-radix Montgomery MAC
    + generic-REDC recurrence at the hostbn tier under the PairMat
    contracts is clean (11 rows of L32·L4 products + q·m rows < 2^62.5,
    the hostec_np proof with the BN modulus' m0inv multiply), and ONE
    extra 4x-widened per-iteration product term pushes the accumulator
    past uint64 and must fire."""
    src_ok = """
        import numpy as np

        NPAIRS = 11
        PAIR_BITS = 2 * 13
        PAIR_MASK = (1 << PAIR_BITS) - 1

        def bn_kernel(a: "PairMatL32", b: "PairMatL4", m_col: "PairMat", m0inv: int):
            lanes = 4
            t = np.zeros((2 * NPAIRS, lanes), dtype=np.uint64)
            for i in range(NPAIRS):
                t[i : i + NPAIRS] += a[i] * b
            for i in range(NPAIRS):
                q = ((t[i] & PAIR_MASK) * m0inv) & PAIR_MASK
                t[i : i + NPAIRS - 1] += q * m_col[0 : NPAIRS - 1]
                t[i + 1] += t[i] >> PAIR_BITS
            return t
        """
    assert flow(
        src_ok, path="fabric_tpu/crypto/hostbn.py", rules=["limb-overflow"]
    ) == []
    src_bad = src_ok.replace(
        "t[i : i + NPAIRS] += a[i] * b",
        "t[i : i + NPAIRS] += a[i] * b + (a[i] << np.uint64(2)) * b",
    )
    findings = flow(
        src_bad, path="fabric_tpu/crypto/hostbn.py", rules=["limb-overflow"]
    )
    assert "limb-overflow" in rule_ids(findings)
    assert any("exceeds uint64" in f.message for f in findings)


def test_hostbn_is_in_the_limb_tier():
    """crypto/hostbn.py carries the pair-limb contracts (the PR 7
    tier-extension satellite): the tier glob must match it."""
    ctx = fabflow.FileContext("fabric_tpu/crypto/hostbn.py")
    assert ctx.matches(fabflow.LIMB_TIER)


def test_bignum_cios_proof_holds_standalone():
    """The headline proof: bignum.py alone, under the canonical-limb
    contract, has no unsuppressed overflow — the 20-iteration CIOS
    accumulator stays below 2^32."""
    findings, stats = fabflow.analyze_paths(
        [str(REPO_ROOT / "fabric_tpu" / "ops" / "bignum.py")],
        rule_ids=["limb-overflow", "dtype-narrowing", "float-contamination"],
    )
    assert findings == []
    assert stats["suppressed"] == 1  # qm_term's documented relational bet


# ---------------------------------------------------------------------------
# fabchaos interplay: fault-injection wrappers must not be able to hide
# a fail-open handler from the analyzer (pinned firing fixture, PR 6)
# ---------------------------------------------------------------------------


def test_mask_fail_open_fires_on_fail_open_injection_wrapper():
    """A *genuinely fail-open* chaos wrapper — swallowing InjectedFault
    around a flag write and moving on — must still fire: fault_point
    sites in the mask tier may only appear inside handlers that settle
    an INVALID-family code, raise, or hand the exception onward (the
    shapes the real batcher/pipeline seams use)."""
    findings = flow(
        """
        from fabric_tpu.common.faults import InjectedFault, fault_point
        from fabric_tpu.common.txflags import TxValidationCode

        def settle(flags, i, data):
            try:
                fault_point("pipeline.commit", key=i)
                flags.set_flag(i, compute_code(data))
            except InjectedFault:
                pass  # swallowed: the lane's flag is left unset
        """,
        path=MASK_PATH,
        rules=["mask-fail-open"],
    )
    assert rule_ids(findings) == ["mask-fail-open"]


def test_mask_fail_open_accepts_fail_closed_injection_wrapper():
    """The real seam shape: an injected fault settles the lane with an
    INVALID-family code (fail-closed) — no finding."""
    findings = flow(
        """
        from fabric_tpu.common.faults import InjectedFault, fault_point
        from fabric_tpu.common.txflags import TxValidationCode

        def settle(flags, i, data):
            try:
                fault_point("pipeline.commit", key=i)
                flags.set_flag(i, compute_code(data))
            except InjectedFault:
                flags.set_flag(i, TxValidationCode.INVALID_OTHER_REASON)
        """,
        path=MASK_PATH,
        rules=["mask-fail-open"],
    )
    assert findings == []
