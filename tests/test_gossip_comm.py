"""Gossip over gRPC: membership convergence, leader block push, and
anti-entropy catch-up between real socket peers (reference gossip/comm,
gossip/state anti-entropy)."""

import time


from conftest import requires_crypto

from fabric_tpu.gossip.comm import GossipNode
from fabric_tpu.gossip.state import StateProvider
from fabric_tpu.protos import protoutil


def make_chain(n):
    """n sealed blocks chained by previous_hash."""
    blocks = []
    prev = b""
    for i in range(n):
        b = protoutil.new_block(i, prev)
        b.data.data.append(f"tx{i}".encode())
        protoutil.seal_block(b)
        prev = protoutil.block_header_hash(b.header)
        blocks.append(b)
    return blocks


class FakeLedger:
    def __init__(self, blocks=()):
        self.blocks = list(blocks)

    def commit(self, block):
        assert block.header.number == len(self.blocks)
        self.blocks.append(block)

    def get_block(self, n):
        return self.blocks[n] if n < len(self.blocks) else None

    @property
    def height(self):
        return len(self.blocks)


def make_node(name, ledger, tick=0.1):
    state = StateProvider(
        "gchannel", ledger.commit, lambda: ledger.height
    )
    return GossipNode(
        name,
        "gchannel",
        state,
        ledger.get_block,
        lambda: ledger.height,
        tick_interval=tick,
    )


def wait_until(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_membership_and_data_push():
    l1, l2 = FakeLedger(), FakeLedger()
    n1, n2 = make_node("peer1", l1), make_node("peer2", l2)
    n1.start()
    n2.start()
    try:
        n2.connect(n1.addr)
        assert wait_until(
            lambda: "peer2" in n1.membership.alive_peers()
            and "peer1" in n2.membership.alive_peers()
        ), "membership did not converge"

        # push a chain through node1 as if it were the deliver leader
        for block in make_chain(3):
            l1.commit(block)
            n1.broadcast_block(block)
        assert wait_until(lambda: l2.height == 3), f"peer2 height {l2.height}"
        assert l2.blocks[2].data.data[0] == b"tx2"
    finally:
        n1.stop()
        n2.stop()


def test_stop_reaps_tick_thread():
    # regression (fablife thread-unjoined): stop() used to leave the
    # tick loop running — a thread leaked per gossip node, and a
    # mid-_tick_once survivor raced the conn teardown below it
    ledger = FakeLedger(make_chain(1))
    node = make_node("reaper", ledger, tick=0.05)
    node.start()
    t = node._thread
    assert t is not None and t.is_alive()
    try:
        node.stop()
        assert not t.is_alive(), "stop() must join the tick loop"
    finally:
        node.stop()  # idempotent-safe cleanup if the assert fired


def test_anti_entropy_catches_up_lagging_peer():
    chain = make_chain(5)
    tall, lagging = FakeLedger(chain), FakeLedger()
    n1, n2 = make_node("tall", tall), make_node("lagging", lagging)
    n1.start()
    n2.start()
    try:
        n2.connect(n1.addr)
        # no data push at all: the lagging peer must learn the height from
        # alive messages and pull the range via StateRequest
        assert wait_until(lambda: lagging.height == 5, timeout=15), (
            f"lagging height {lagging.height}"
        )
        assert (
            protoutil.block_header_hash(lagging.blocks[4].header)
            == protoutil.block_header_hash(chain[4].header)
        )
    finally:
        n1.stop()
        n2.stop()


@requires_crypto
def test_peer_nodes_gossip_network(tmp_path):
    """Three PeerNodes, one orderer: only the elected leader pulls from
    the orderer; followers receive blocks via gossip push/anti-entropy
    (gossip_service.go InitializeChannel + deliverservice leadership)."""
    from fabric_tpu.channelconfig import (
        ApplicationProfile,
        OrdererProfile,
        OrganizationProfile,
        Profile,
        genesis_block,
    )
    from fabric_tpu.crypto.bccsp import SoftwareProvider
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.ledger import rwset as rw
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
    from fabric_tpu.msp.cryptogen import generate_org
    from fabric_tpu.msp.identity import MSPManager
    from fabric_tpu.msp.signer import SigningIdentity
    from fabric_tpu.nodes import OrdererNode, PeerNode
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.comm.server import channel_to
    from fabric_tpu.comm.services import broadcast_envelope
    from fabric_tpu.validation.validator import (
        ChaincodeDefinition,
        ChaincodeRegistry,
    )

    provider = SoftwareProvider()
    org1 = generate_org("org1.example.com", "Org1MSP", num_peers=3)
    oorg = generate_org("orderer.example.com", "OrdererMSP")
    mgr = MSPManager([org1.msp(provider=provider)])
    policy = from_dsl("OR('Org1MSP.member')")

    def rf(cid):
        return ChaincodeRegistry([ChaincodeDefinition("mycc", policy)])

    profile = Profile(
        application=ApplicationProfile(
            organizations=[OrganizationProfile("Org1MSP", org1.msp_config())]
        ),
        orderer=OrdererProfile(
            orderer_type="solo",
            organizations=[OrganizationProfile("OrdererMSP", oorg.msp_config())],
        ),
    )
    gblock = genesis_block(profile, "gchan")
    orderer = OrdererNode(
        str(tmp_path / "ord"), signer=SigningIdentity(oorg.peers[0], provider)
    )
    orderer.join_channel(gblock)
    orderer.start()

    peers = []
    gnodes = []
    try:
        for i in range(3):
            p = PeerNode(
                str(tmp_path / f"p{i}"),
                mgr,
                SigningIdentity(org1.peers[i], provider),
                rf,
                provider=provider,
            )
            p.join_channel(gblock)
            p.start()
            bootstrap = [gnodes[0].addr] if gnodes else []
            g = p.enable_gossip_for_channel(
                "gchan", bootstrap=bootstrap, orderer_addr=orderer.addr
            )
            peers.append(p)
            gnodes.append(g)

        assert wait_until(
            lambda: sum(1 for g in gnodes if g.is_leader) == 1, timeout=15
        ), [g.is_leader for g in gnodes]

        client = SigningIdentity(org1.users[0], provider)
        results = serialize_tx_rwset(
            rw.TxRwSet(
                (rw.NsRwSet("mycc", (), (rw.KVWrite("gk", False, b"gv"),)),)
            )
        )
        bundle = create_proposal(client, "gchan", "mycc", [b"put", b"gk"])
        env = create_signed_tx(
            bundle,
            client,
            [endorse_proposal(bundle, SigningIdentity(org1.peers[0], provider), results)],
        )
        conn = channel_to(orderer.addr)
        ack = broadcast_envelope(conn, env)
        conn.close()
        assert ack.status == 200 or ack.status == 0 or ack.status  # SUCCESS enum

        # every peer converges to height 2 — one via deliver, two via gossip
        assert wait_until(
            lambda: all(
                p.channels["gchan"].ledger.height == 2 for p in peers
            ),
            timeout=25,
        ), [p.channels["gchan"].ledger.height for p in peers]
        for p in peers:
            assert p.channels["gchan"].ledger.get_state("mycc", "gk") == b"gv"
    finally:
        for p in peers:
            p.stop()
        orderer.stop()


def test_leader_election_converges():
    l1, l2, l3 = FakeLedger(), FakeLedger(), FakeLedger()
    nodes = [
        make_node("peerA", l1),
        make_node("peerB", l2),
        make_node("peerC", l3),
    ]
    for n in nodes:
        n.start()
    try:
        for n in nodes[1:]:
            n.connect(nodes[0].addr)
        # full mesh discovery via forwarded endpoints may take a few ticks
        assert wait_until(
            lambda: all(len(n.membership.alive_peers()) >= 2 for n in nodes),
            timeout=15,
        ), [n.membership.alive_peers() for n in nodes]
        assert wait_until(
            lambda: sum(1 for n in nodes if n.is_leader) == 1, timeout=15
        ), [n.is_leader for n in nodes]
    finally:
        for n in nodes:
            n.stop()


def test_certstore_identity_pull():
    """Identity certstore sync via the Hello->Digest->Request->Update
    pull rounds (gossip/gossip/pull + certstore)."""
    l1, l2, l3 = FakeLedger(), FakeLedger(), FakeLedger()
    nodes = [
        GossipNode(
            f"p{i}",
            "gchannel",
            StateProvider("gchannel", lg.commit, lambda lg=lg: lg.height),
            lg.get_block,
            lambda lg=lg: lg.height,
            tick_interval=0.1,
            identity_bytes=f"identity-of-p{i}".encode(),
        )
        for i, lg in enumerate((l1, l2, l3))
    ]
    for n in nodes:
        n.start()
    try:
        nodes[1].connect(nodes[0].addr)
        nodes[2].connect(nodes[0].addr)
        # every node eventually holds every identity, including ones from
        # peers it never connected to directly
        assert wait_until(
            lambda: all(
                n.certstore.get(f"p{i}".encode()) == f"identity-of-p{i}".encode()
                for n in nodes
                for i in range(3)
            ),
            timeout=15,
        ), [
            (n.self_id, n.certstore.digests()) for n in nodes
        ]
    finally:
        for n in nodes:
            n.stop()


def test_pvt_dissemination_and_reconciliation():
    """Endorsement-time private-data push lands in remote transient
    stores; missing pvt data is pulled back by the reconciler
    (gossip/privdata pull.go + reconcile.go)."""
    from fabric_tpu.gossip.coordinator import TransientStore

    l1, l2 = FakeLedger(), FakeLedger()
    t1, t2 = TransientStore(), TransientStore()

    served = {("secret", 3): b"pvt-kvrwset-bytes"}

    def pvt_reader_1(block_num, tx_num, ns, coll):
        return served.get((coll, block_num)) if tx_num == 0 else None

    reconciled = []

    n1 = GossipNode(
        "p1",
        "gchannel",
        StateProvider("gchannel", l1.commit, lambda: l1.height),
        l1.get_block,
        lambda: l1.height,
        tick_interval=0.1,
        identity_bytes=b"id1",
        transient_store=t1,
        pvt_reader=pvt_reader_1,
    )
    n2 = GossipNode(
        "p2",
        "gchannel",
        StateProvider("gchannel", l2.commit, lambda: l2.height),
        l2.get_block,
        lambda: l2.height,
        tick_interval=0.1,
        identity_bytes=b"id2",
        transient_store=t2,
        pvt_reader=lambda *a: None,
    )

    from fabric_tpu.ledger.pvtdatastore import MissingEntry

    missing = {3: [MissingEntry(0, "mycc", "secret")]}

    def missing_provider():
        return dict(missing)

    def reconcile_commit(items):
        reconciled.extend(items)
        missing.clear()

    n2.enable_reconciliation(missing_provider, reconcile_commit)
    n1.start()
    n2.start()
    try:
        n2.connect(n1.addr)
        assert wait_until(
            lambda: "p2" in n1.membership.alive_peers()
            and "p1" in n2.membership.alive_peers()
        )
        # endorsement-time push: n1 -> n2's transient store
        n1.disseminate_pvt(
            "tx42", [("mycc", "secret", b"cleartext-writes")]
        )
        assert wait_until(
            lambda: t2.get("tx42", "mycc", "secret") == b"cleartext-writes"
        )
        # reconciliation: n2 recovers block 3's missing collection from n1
        assert wait_until(lambda: reconciled != [], timeout=15)
        assert reconciled == [(3, 0, "mycc", "secret", b"pvt-kvrwset-bytes")]
    finally:
        n1.stop()
        n2.stop()


@requires_crypto
def test_signed_alive_membership(tmp_path):
    """Signed membership (reference SignedGossipMessage): in strict mode a
    node adopts alives only when the signature verifies against the
    certstore identity for the claimed pki_id; forged and unsigned alives
    are dropped."""
    from fabric_tpu.crypto.bccsp import SoftwareProvider
    from fabric_tpu.gossip.comm import GossipNode, _alive_signing_bytes
    from fabric_tpu.gossip.state import StateProvider
    from fabric_tpu.msp.cryptogen import generate_org
    from fabric_tpu.msp.identity import MSPManager
    from fabric_tpu.msp.signer import SigningIdentity
    from fabric_tpu.protos import gossip_pb2

    provider = SoftwareProvider()
    org = generate_org("org1.signedalive", "Org1MSP")
    mgr = MSPManager([org.msp(provider=provider)])
    honest = SigningIdentity(org.peers[0], provider)
    rogue = SigningIdentity(org.users[0], provider)

    def verify_member_sig(identity, data, sig):
        try:
            ident, msp = mgr.deserialize_identity(identity)
            msp.validate(ident)
            ident.verify(data, sig)
            return True
        except Exception:  # noqa: BLE001
            return False

    node = GossipNode(
        "Org1MSP:server",
        "alivechan",
        StateProvider("alivechan", lambda b: None, lambda: 1),
        lambda n: None,
        lambda: 1,
        identity_bytes=honest.serialize(),
        pvt_verify_member_sig=verify_member_sig,
        sign_message=honest.sign,
        require_signed_alive=True,
    )
    # the server knows the honest member's identity (certstore)
    node.certstore.put(b"Org1MSP:peerA", honest.serialize())

    def alive(pki, endpoint, seq, signer=None, tamper=False):
        msg = gossip_pb2.GossipMessage()
        msg.channel = "alivechan"
        msg.alive_msg.membership.pki_id = pki
        msg.alive_msg.membership.endpoint = endpoint
        msg.alive_msg.membership.ledger_height = 5
        msg.alive_msg.seq_num = seq
        if signer is not None:
            msg.alive_msg.signature = signer.sign(
                _alive_signing_bytes(msg.alive_msg, "alivechan")
            )
        if tamper:
            msg.alive_msg.membership.endpoint = "evil:1"
        return msg

    members = lambda: set(node.membership.alive_peers())  # noqa: E731

    node._handle(alive(b"Org1MSP:peerA", "good:1", 1, signer=honest))
    assert "Org1MSP:peerA" in members()
    # unsigned alive dropped in strict mode
    node._handle(alive(b"Org1MSP:peerB", "b:1", 1))
    assert "Org1MSP:peerB" not in members()
    # signature by the WRONG identity (rogue signs, claims peerA) dropped
    node._handle(alive(b"Org1MSP:peerA", "hijack:1", 2, signer=rogue))
    assert node._endpoints.get("Org1MSP:peerA") == "good:1"
    # tampered-after-signing endpoint dropped
    node._handle(alive(b"Org1MSP:peerA", "good:1", 3, signer=honest, tamper=True))
    assert node._endpoints.get("Org1MSP:peerA") == "good:1"
    # unknown pki_id (no certstore identity) refused in strict mode
    node._handle(alive(b"Org1MSP:ghost", "g:1", 1, signer=honest))
    assert "Org1MSP:ghost" not in members()
    # an alive validly signed for ANOTHER channel does not verify here
    # (the channel id is bound into the signed bytes)
    cross = gossip_pb2.GossipMessage()
    cross.channel = "alivechan"
    cross.alive_msg.membership.pki_id = b"Org1MSP:peerA"
    cross.alive_msg.membership.endpoint = "cross:1"
    cross.alive_msg.seq_num = 9
    cross.alive_msg.signature = honest.sign(
        _alive_signing_bytes(cross.alive_msg, "otherchan")
    )
    node._handle(cross)
    assert node._endpoints.get("Org1MSP:peerA") == "good:1"
    # a replayed OLD signed alive cannot roll the endpoint back
    node._handle(alive(b"Org1MSP:peerA", "moved:1", 10, signer=honest))
    assert node._endpoints.get("Org1MSP:peerA") == "moved:1"
    node._handle(alive(b"Org1MSP:peerA", "good:1", 3, signer=honest))
    assert node._endpoints.get("Org1MSP:peerA") == "moved:1"
    # certstore bindings are first-bind-wins: the same-MSP rogue cannot
    # re-bind peerA's pki_id to its own cert
    assert node.certstore.put(b"Org1MSP:peerA", rogue.serialize()) is False
    node.server.stop()


def test_dropped_bootstrap_hello_recovers_via_anchor_retry():
    """A lost connect() hello must not partition the pair forever: the
    tick loop re-introduces bootstrap anchors until a member answers
    from that endpoint (the brittleness the fabchaos gossip_storm
    scenario surfaced — pre-fix, ticks only addressed peers ALREADY in
    the member view, so one dropped hello was permanent)."""
    from fabric_tpu.common.faults import FaultPlan, plan_installed

    l1, l2 = FakeLedger(), FakeLedger()
    n1, n2 = make_node("a1", l1), make_node("a2", l2)
    n1.start()
    n2.start()
    try:
        # drop exactly the first stream open: the bootstrap hello itself
        plan = FaultPlan.parse(
            "gossip.comm.send=drop:1.0:max=1", seed=7
        )
        with plan_installed(plan):
            n2.connect(n1.addr)
            assert plan.fired().get("gossip.comm.send", 0) == 1, (
                "the hello was not dropped — test setup is stale"
            )
            assert wait_until(
                lambda: "a2" in n1.membership.alive_peers()
                and "a1" in n2.membership.alive_peers()
            ), "anchor re-introduction never healed the dropped hello"
    finally:
        n1.stop()
        n2.stop()
