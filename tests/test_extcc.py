"""Out-of-process chaincode: packaging/install (lifecycle.go install
path), the shim stream protocol (handler.go message loop), the subprocess
launcher, and the external-builder exec contract
(core/container/externalbuilder)."""

import os
import stat
import textwrap
import time

import pytest

from fabric_tpu.chaincode import shim
from fabric_tpu.chaincode.extbuilder import ExternalBuilder, Launcher
from fabric_tpu.chaincode.extserver import ChaincodeListener
from fabric_tpu.chaincode.extshim import start as shim_start
from fabric_tpu.chaincode.package import (
    PackageError,
    PackageStore,
    package,
    package_id,
    parse_package,
)
from fabric_tpu.chaincode.support import ChaincodeSupport, TxParams
from fabric_tpu.comm.server import GRPCServer
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.ledger.statedb import VersionedDB

CC_SOURCE = textwrap.dedent(
    '''
    """Sample asset chaincode run OUT of process by the launcher."""
    from fabric_tpu.chaincode.shim import Response, success, error_response


    class Chaincode:
        def init(self, stub):
            return success(b"init-ok")

        def invoke(self, stub):
            fn, params = stub.get_function_and_parameters()
            if fn == "put":
                stub.put_state(params[0], params[1].encode())
                return success(b"stored")
            if fn == "get":
                value = stub.get_state(params[0])
                if value is None:
                    return error_response(f"{params[0]} not found")
                return success(value)
            if fn == "del":
                stub.del_state(params[0])
                return success(b"")
            return error_response(f"unknown function {fn!r}")


    chaincode = Chaincode()
    '''
).encode()


# ----------------------------------------------------------------------
# packaging
# ----------------------------------------------------------------------


def test_package_roundtrip_and_id():
    raw = package("asset", {"chaincode.py": CC_SOURCE})
    meta, files = parse_package(raw)
    assert meta == {"label": "asset", "type": "python"}
    assert files == {"chaincode.py": CC_SOURCE}
    pid = package_id(raw)
    label, _, digest = pid.partition(":")
    assert label == "asset" and len(digest) == 64
    # deterministic bytes -> stable id
    assert package_id(package("asset", {"chaincode.py": CC_SOURCE})) == pid
    with pytest.raises(PackageError):
        package("bad:label", {})
    with pytest.raises(PackageError):
        parse_package(b"not a tarball")


def test_package_store_install_and_list(tmp_path):
    store = PackageStore(str(tmp_path))
    raw = package("asset", {"chaincode.py": CC_SOURCE})
    installed = store.install(raw)
    assert installed.package_id == package_id(raw)
    assert store.load(installed.package_id) == raw
    listed = store.list_installed()
    assert [p.package_id for p in listed] == [installed.package_id]
    with pytest.raises(PackageError):
        store.load("ghost:00")


# ----------------------------------------------------------------------
# shim stream protocol (in-process client thread)
# ----------------------------------------------------------------------


class RangeCC:
    def init(self, stub):
        return shim.success(b"")

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "fill":
            for k in params:
                stub.put_state(k, f"v-{k}".encode())
            return shim.success(b"")
        if fn == "scan":
            rows = list(stub.get_state_by_range(params[0], params[1]))
            return shim.success(
                ",".join(k for k, _ in rows).encode()
            )
        if fn == "event":
            stub.set_event("my-event", b"event-payload")
            return shim.success(b"")
        return shim.error_response("nope")


@pytest.fixture
def listener_server():
    listener = ChaincodeListener()
    server = GRPCServer("127.0.0.1:0")
    listener.register(server)
    addr = server.start()
    yield listener, addr
    server.stop()


def _support(listener, db):
    return ChaincodeSupport(listener=listener)


def test_stream_protocol_state_ops(listener_server):
    listener, addr = listener_server
    session = shim_start(RangeCC(), addr, "rangecc:aa", block=False)
    assert listener.wait_for("rangecc:aa", timeout=10)

    db = VersionedDB()
    support = _support(listener, db)
    sim = TxSimulator(db, "tx1")
    params = TxParams(channel_id="ch", tx_id="tx1", simulator=sim)
    cc = listener.chaincode("rangecc:aa")
    support._chaincodes["rangecc"] = cc  # direct registration path

    # committed state for the scan (range scans read committed state, not
    # the tx's own writes — reference simulator semantics)
    from fabric_tpu.ledger.rwset import Version
    from fabric_tpu.ledger.statedb import UpdateBatch

    seed = UpdateBatch()
    for i, k in enumerate(("a", "b", "c")):
        seed.put("rangecc", k, f"v-{k}".encode(), Version(0, i))
    db.apply_updates(seed)

    resp, _ = support.execute(params, "rangecc", [b"fill", b"x", b"y"])
    assert resp.status == shim.OK
    resp, _ = support.execute(params, "rangecc", [b"scan", b"a", b"z"])
    assert resp.status == shim.OK and resp.payload == b"a,b,c"

    # events propagate through COMPLETED.chaincode_event
    resp, event = support.execute(params, "rangecc", [b"event"])
    assert resp.status == shim.OK
    assert event is not None and event.event_name == "my-event"

    # writes landed in the simulator's rwset, not anywhere else
    results = sim.get_tx_simulation_results()
    ns = [n for n in results.rwset.ns_rw_sets if n.namespace == "rangecc"]
    assert ns and [w.key for w in ns[0].writes] == ["x", "y"]
    session.stop()


# ----------------------------------------------------------------------
# subprocess launcher via the built-in python builder
# ----------------------------------------------------------------------


def test_launcher_runs_chaincode_subprocess(tmp_path, listener_server):
    listener, addr = listener_server
    store = PackageStore(str(tmp_path / "pkgs"))
    installed = store.install(package("asset", {"chaincode.py": CC_SOURCE}))
    launcher = Launcher(str(tmp_path / "build"))

    db = VersionedDB()
    support = ChaincodeSupport(
        listener=listener,
        launcher=launcher,
        package_store=store,
        source_resolver=lambda cid, name: (
            installed.package_id if name == "asset" else None
        ),
        chaincode_address=lambda: addr,
    )
    try:
        from fabric_tpu.ledger.rwset import Version
        from fabric_tpu.ledger.statedb import UpdateBatch

        seed = UpdateBatch()
        seed.put("asset", "k0", b"seeded", Version(0, 0))
        db.apply_updates(seed)

        sim = TxSimulator(db, "tx9")
        params = TxParams(channel_id="ch", tx_id="tx9", simulator=sim)
        resp, _ = support.execute(params, "asset", [b"put", b"k1", b"hello"])
        assert resp.status == shim.OK, resp.message
        # really out of process
        proc = launcher._procs[installed.package_id]
        assert proc.pid != os.getpid() and proc.poll() is None
        # committed state reads round-trip over the stream (reads never
        # see the tx's own writes — reference simulator semantics)
        resp, _ = support.execute(params, "asset", [b"get", b"k0"])
        assert resp.status == shim.OK and resp.payload == b"seeded"
        # the put above is in the rwset
        results = sim.get_tx_simulation_results()
        ns = [n for n in results.rwset.ns_rw_sets if n.namespace == "asset"]
        assert ns and [w.key for w in ns[0].writes] == ["k1"]
        # relaunch is a no-op while the process lives
        assert launcher.launch(installed, addr) is proc
    finally:
        launcher.stop()


# ----------------------------------------------------------------------
# external-builder exec contract
# ----------------------------------------------------------------------


def _write_exe(path, body):
    with open(path, "w") as f:
        f.write(body)
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)


def test_external_builder_contract(tmp_path, listener_server):
    listener, addr = listener_server
    bdir = tmp_path / "mybuilder" / "bin"
    os.makedirs(bdir)
    # claims packages whose metadata type is "shellcc"; build copies the
    # source; run launches the python launcher manually (stand-in for an
    # arbitrary runtime)
    _write_exe(
        bdir / "detect",
        "#!/bin/sh\ngrep -q '\"type\": \"shellcc\"' \"$2/metadata.json\"\n",
    )
    _write_exe(bdir / "build", "#!/bin/sh\ncp -r \"$1\"/. \"$3\"/\n")
    _write_exe(
        bdir / "run",
        "#!/bin/sh\n"
        'CCID=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))[\'chaincode_id\'])" "$2/chaincode.json")\n'
        'ADDR=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))[\'peer_address\'])" "$2/chaincode.json")\n'
        "exec python -m fabric_tpu.chaincode.launcher --source-dir \"$1\" "
        "--peer-address \"$ADDR\" --chaincode-id \"$CCID\"\n",
    )
    builder = ExternalBuilder(str(tmp_path / "mybuilder"))
    store = PackageStore(str(tmp_path / "pkgs"))
    raw = package("shellasset", {"chaincode.py": CC_SOURCE}, cc_type="shellcc")
    installed = store.install(raw)
    launcher = Launcher(str(tmp_path / "build"), builders=[builder])
    try:
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        os.environ["PYTHONPATH"] = (
            repo + os.pathsep + env.get("PYTHONPATH", "")
        )
        launcher.launch(installed, addr)
        assert listener.wait_for(installed.package_id, timeout=90)
        db = VersionedDB()
        sim = TxSimulator(db, "tx1")
        cc = listener.chaincode(installed.package_id)
        stub_support = ChaincodeSupport(listener=listener)
        stub_support._chaincodes["shellasset"] = cc
        params = TxParams(channel_id="ch", tx_id="tx1", simulator=sim)
        resp, _ = stub_support.execute(params, "shellasset", [b"put", b"x", b"1"])
        assert resp.status == shim.OK, resp.message
    finally:
        launcher.stop()
        os.environ.update(env)
