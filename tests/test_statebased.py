"""State-based (key-level) endorsement tests — reference semantics from
core/common/validation/statebased/validator_keylevel_test.go:

- a key with VALIDATION_PARAMETER metadata is validated against that
  policy instead of the chaincode EP;
- a tx whose written key had its validation parameters updated by an
  earlier VALID tx in the same block is invalidated;
- if the earlier metadata-writer tx was itself invalid, the committed
  parameter applies;
- metadata-only writes carry state through commit (tx_ops.go merge).
"""

import pytest

from conftest import requires_crypto
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.mvcc import deserialize_metadata, serialize_metadata_entries
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.orderer import SoloChain
from fabric_tpu.orderer.blockcutter import BatchConfig
from fabric_tpu.peer import Channel
from fabric_tpu.policy import from_dsl
from fabric_tpu.policy.proto_convert import marshal_application_policy
from fabric_tpu.protos import common_pb2
from fabric_tpu.validation.statebased import VALIDATION_PARAMETER
from fabric_tpu.validation.txflags import TxValidationCode
from fabric_tpu.validation.validator import ChaincodeDefinition, ChaincodeRegistry

CHANNEL = "sbechannel"
PROVIDER = SoftwareProvider()


@pytest.fixture(scope="module")
def net():
    org1 = generate_org("org1.example.com", "Org1MSP")
    org2 = generate_org("org2.example.com", "Org2MSP")
    orderer_org = generate_org("orderer.example.com", "OrdererMSP")
    mgr = MSPManager([org1.msp(provider=PROVIDER), org2.msp(provider=PROVIDER)])
    # chaincode EP: either org alone endorses fine
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("sbecc", from_dsl("OR('Org1MSP.member','Org2MSP.member')"))]
    )
    return {
        "mgr": mgr,
        "registry": registry,
        "client": SigningIdentity(org1.users[0], PROVIDER),
        "p1": SigningIdentity(org1.peers[0], PROVIDER),
        "p2": SigningIdentity(org2.peers[0], PROVIDER),
        "oid": SigningIdentity(orderer_org.peers[0], PROVIDER),
    }


def make_tx(net, writes=(), metadata_writes=(), endorsers=("p1",)):
    results = serialize_tx_rwset(
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "sbecc",
                    writes=tuple(
                        rw.KVWrite(k, False, v) for k, v in writes
                    ),
                    metadata_writes=tuple(metadata_writes),
                ),
            )
        )
    )
    bundle = create_proposal(net["client"], CHANNEL, "sbecc", [b"put"])
    responses = [
        endorse_proposal(bundle, net[e], results) for e in endorsers
    ]
    return create_signed_tx(bundle, net["client"], responses)


def vp_entries(policy_dsl):
    """VALIDATION_PARAMETER metadata entries carrying an ApplicationPolicy."""
    return (
        (VALIDATION_PARAMETER, marshal_application_policy(from_dsl(policy_dsl))),
    )


def run_block(net, tmp_path, name, envs_per_block):
    chain = SoloChain(
        CHANNEL, signer=net["oid"],
        batch_config=BatchConfig(max_message_count=100),
    )
    blocks = []
    chain.deliver = blocks.append
    peer = Channel(CHANNEL, str(tmp_path / name), net["mgr"], net["registry"], PROVIDER)
    flags_out = []
    for envs in envs_per_block:
        for env in envs:
            chain.order(env)
        chain.flush()
        flags_out.append(peer.store_block(blocks[-1]))
    return peer, flags_out


@requires_crypto
def test_vp_metadata_persisted_and_enforced(net, tmp_path):
    """Block 1 sets a key-level policy requiring Org2; block 2's tx
    endorsed only by Org1 on that key is invalidated."""
    set_vp = make_tx(
        net,
        writes=[("k", b"v0")],
        metadata_writes=[rw.KVMetadataWrite("k", vp_entries("AND('Org2MSP.member')"))],
        endorsers=("p1",),
    )
    org1_write = make_tx(net, writes=[("k", b"v1")], endorsers=("p1",))
    org2_write = make_tx(net, writes=[("k", b"v2")], endorsers=("p2",))

    peer, flags = run_block(
        net, tmp_path, "peer", [[set_vp], [org1_write], [org2_write]]
    )
    V = TxValidationCode
    assert [int(c) for c in flags[0].asarray()] == [int(V.VALID)]
    # committed metadata present
    md = deserialize_metadata(peer.ledger.state_db.get_state_metadata("sbecc", "k"))
    assert VALIDATION_PARAMETER in md
    # org1-only endorsement now fails the key-level policy
    assert [int(c) for c in flags[1].asarray()] == [int(V.ENDORSEMENT_POLICY_FAILURE)]
    assert peer.ledger.get_state("sbecc", "k") == b"v2"
    assert [int(c) for c in flags[2].asarray()] == [int(V.VALID)]


@requires_crypto
def test_in_block_vp_update_invalidates_later_tx(net, tmp_path):
    """tx0 updates k's validation parameter; tx1 (same block) writes k ->
    invalidated because its endorsements predate the new policy."""
    tx0 = make_tx(
        net,
        writes=[("k", b"v0")],
        metadata_writes=[rw.KVMetadataWrite("k", vp_entries("AND('Org1MSP.member')"))],
        endorsers=("p1",),
    )
    tx1 = make_tx(net, writes=[("k", b"v1")], endorsers=("p1", "p2"))
    _, flags = run_block(net, tmp_path, "peer", [[tx0, tx1]])
    V = TxValidationCode
    assert [int(c) for c in flags[0].asarray()] == [
        int(V.VALID),
        int(V.ENDORSEMENT_POLICY_FAILURE),
    ]


@requires_crypto
def test_invalid_metadata_writer_does_not_block(net, tmp_path):
    """If the metadata-writing tx is itself invalid (policy failure), a
    later tx in the same block validates against the committed state."""
    # chaincode EP is OR(...), but craft the metadata writer to fail:
    # it writes to a key whose VP (set in block 1) requires Org2 while
    # it is endorsed by Org1 only.
    setup = make_tx(
        net,
        writes=[("k", b"v0")],
        metadata_writes=[rw.KVMetadataWrite("k", vp_entries("AND('Org2MSP.member')"))],
        endorsers=("p1",),
    )
    bad_writer = make_tx(
        net,
        writes=[("k", b"x")],
        metadata_writes=[rw.KVMetadataWrite("k", vp_entries("AND('Org1MSP.member')"))],
        endorsers=("p1",),  # fails the Org2 key policy
    )
    org2_write = make_tx(net, writes=[("k", b"v2")], endorsers=("p2",))
    _, flags = run_block(net, tmp_path, "peer", [[setup], [bad_writer, org2_write]])
    V = TxValidationCode
    assert [int(c) for c in flags[1].asarray()] == [
        int(V.ENDORSEMENT_POLICY_FAILURE),
        int(V.VALID),  # not blocked by the invalid in-block update
    ]


@requires_crypto
def test_metadata_only_write_merges_value(net, tmp_path):
    """A metadata-only write keeps the committed value (tx_ops merge) and
    a metadata write on a missing key is a no-op."""
    put = make_tx(net, writes=[("k", b"v0")], endorsers=("p1",))
    md_only = make_tx(
        net,
        metadata_writes=[rw.KVMetadataWrite("k", vp_entries("OR('Org1MSP.member','Org2MSP.member')"))],
        endorsers=("p1",),
    )
    md_missing = make_tx(
        net,
        metadata_writes=[rw.KVMetadataWrite("ghost", vp_entries("AND('Org1MSP.member')"))],
        endorsers=("p1",),
    )
    peer, flags = run_block(net, tmp_path, "peer", [[put], [md_only, md_missing]])
    assert all(int(c) == int(TxValidationCode.VALID) for c in flags[1].asarray())
    assert peer.ledger.get_state("sbecc", "k") == b"v0"  # value preserved
    assert peer.ledger.state_db.get_state_metadata("sbecc", "k") is not None
    assert peer.ledger.get_state("sbecc", "ghost") is None  # no-op
    assert peer.ledger.state_db.get_state_metadata("sbecc", "ghost") is None


def test_metadata_serialization_roundtrip():
    entries = (("a", b"1"), (VALIDATION_PARAMETER, b"\x01\x02"))
    raw = serialize_metadata_entries(entries)
    assert deserialize_metadata(raw) == {"a": b"1", VALIDATION_PARAMETER: b"\x01\x02"}
    assert deserialize_metadata(None) is None
