"""Gate-script drift guards.

ci_gate's stage list exists in two places a human must keep in sync:
the ``STAGE_NAMES`` array in ``scripts/ci_gate.sh`` and the README
"Running" table.  PR 15 added stage 10 (life) and this guard so the
NEXT stage cannot be added in one place only.  It also pins the
cross-language facet fablife cannot see: the gate scripts are bash, so
a ``mkdtemp`` in an embedded-python heredoc (the obs_gate shape) or a
``mktemp`` in shell is outside the analyzer's reach — every one must
be paired with its release in the same script."""

import re
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
CI_GATE = REPO_ROOT / "scripts" / "ci_gate.sh"
README = REPO_ROOT / "README.md"


def ci_gate_stage_names():
    m = re.search(r"^STAGE_NAMES=\(([^)]*)\)", CI_GATE.read_text(), re.M)
    assert m, "ci_gate.sh lost its STAGE_NAMES array"
    return m.group(1).split()


def test_ci_gate_stage_list_matches_the_readme_running_table():
    names = ci_gate_stage_names()
    m = re.search(
        r"<!-- ci_gate stages: ([a-z ]+) -->", README.read_text()
    )
    assert m, (
        "README.md lost its machine-readable ci_gate stage marker "
        "(<!-- ci_gate stages: ... --> above the Running block)"
    )
    assert m.group(1).split() == names, (
        f"ci_gate.sh stages {names} != README Running table "
        f"{m.group(1).split()}: a stage was added in one place only"
    )


def test_ci_gate_run_stage_calls_match_the_stage_list():
    text = CI_GATE.read_text()
    names = ci_gate_stage_names()
    calls = re.findall(r"^run_stage (\S+)", text, re.M)
    assert calls == names, (
        f"run_stage call order {calls} != STAGE_NAMES {names}"
    )
    # the life stage exists and wires the fablife gate
    assert "life" in names
    assert "life_gate.sh" in text
    # PR 17: stage 11 wires the fabwire gate
    assert "wire" in names
    assert "wire_gate.sh" in text
    # PR 18: stage 12 wires the fabtrace gate
    assert "trace" in names
    assert "trace_gate.sh" in text
    # PR 19: stage 13 wires the fabdet gate
    assert names[-1] == "det" and len(names) == 13
    assert "det_gate.sh" in text


def test_every_wire_toml_surface_exists_on_disk():
    """A renamed module must not silently drop out of wire analysis:
    fabwire only checks codec/enum/store rows whose module path matches
    a scanned file, so a stale path would make every check on that
    surface vacuously pass.  Every declared path must exist."""
    from fabric_tpu.tools import fabwire

    spec = fabwire.load_default_wire()
    declared = set(spec.surfaces)
    declared.update(c.module for c in spec.codecs)
    declared.update(e.module for e in spec.enums)
    declared.update(s.module for s in spec.stores)
    missing = sorted(
        mod for mod in declared if not (REPO_ROOT / mod).is_file()
    )
    assert missing == [], (
        f"tools/wire.toml names modules that do not exist: {missing} — "
        f"update the table when a framing surface moves"
    )


def test_every_hotpath_toml_surface_exists_on_disk():
    """Same discipline as the wire.toml pin: fabtrace only scans stage
    and device rows whose module path matches a file on disk, so a
    renamed module would make every check on that surface vacuously
    pass.  Every declared path must exist."""
    from fabric_tpu.tools import fabtrace

    spec = fabtrace.load_default_hotpath()
    declared = {s.module for s in spec.stages}
    declared.update(spec.devices)
    missing = sorted(
        mod
        for mod in declared
        if not (REPO_ROOT / "fabric_tpu" / mod).is_file()
    )
    assert missing == [], (
        f"tools/hotpath.toml names modules that do not exist: {missing} "
        f"— update the table when a pipeline stage moves"
    )


def test_every_det_toml_surface_exists_on_disk():
    """Same discipline as the wire.toml/hotpath.toml pins: fabdet only
    binds [[surface]] rows whose module path matches a scanned file, so
    a renamed emitter would make every taint check on that surface
    vacuously pass.  Every declared path must exist.  (The other half —
    a declared FUNCTION gone from a live module — is fabdet's own
    always-on surface-missing finding.)"""
    from fabric_tpu.tools import fabdet

    spec = fabdet.load_default_det()
    declared = {s.module for s in spec.surfaces}
    missing = sorted(
        mod for mod in declared if not (REPO_ROOT / mod).is_file()
    )
    assert missing == [], (
        f"tools/det.toml names modules that do not exist: {missing} — "
        f"update the table when a det emitter moves"
    )


def test_every_gate_script_releases_its_tempdirs():
    # the tempdir classes fablife cannot see: bash mktemp (needs a trap
    # rm) and python mkdtemp inside a heredoc (needs an rmtree in the
    # same script) — the serve/obs gate leak class fixed across PRs
    for script in sorted((REPO_ROOT / "scripts").glob("*.sh")):
        text = script.read_text()
        if "mkdtemp(" in text:
            assert "rmtree(" in text, (
                f"{script.name}: mkdtemp without rmtree — the gate "
                f"leaks a /tmp dir per CI run"
            )
        if re.search(r"\$\(mktemp\b", text):
            # either the trap rm's inline, or it invokes a cleanup
            # function that rm's (the serve_gate shape)
            assert re.search(r"^trap ", text, re.M) and re.search(
                r"\brm -r?f?\b", text
            ), (
                f"{script.name}: mktemp without a trap-covered rm — "
                f"the gate leaks a /tmp file per CI run"
            )
