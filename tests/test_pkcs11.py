"""PKCS#11 provider (reference bccsp/pkcs11): provider logic tested
against a FAKED Cryptoki token (the image ships no HSM): token
signatures get low-S normalization + DER wrap identical to the software
path, SKI-located keys are cached, verify semantics match the SW
contract, and the factory errors hard on a missing library."""

import hashlib

import pytest

pytest.importorskip(
    "cryptography", reason="PKCS11 fake-token tests sign via fastec"
)

from fabric_tpu.crypto import der, fastec, p256  # noqa: E402
from fabric_tpu.crypto.bccsp import ECDSAPublicKey, SoftwareProvider
from fabric_tpu.crypto.factory import FactoryError, provider_from_config
from fabric_tpu.crypto.pkcs11 import PKCS11Error, PKCS11Provider


class FakeToken:
    """Cryptoki stand-in: one resident P-256 keypair addressed by SKI.
    sign_raw deliberately returns HIGH-S half the time so the
    provider's toLowS normalization is exercised (pkcs11.go:486)."""

    def __init__(self):
        self.kp = fastec.generate_keypair()
        self.ski = hashlib.sha256(b"token-key").digest()[:20]
        self.find_calls = 0
        self._flip = False

    def find_key(self, ski, private):
        self.find_calls += 1
        if ski != self.ski:
            raise PKCS11Error(f"no key with SKI {ski.hex()} on token")
        return 7 if private else 8

    def sign_raw(self, handle, digest):
        assert handle == 7
        r, s = fastec.sign_digest(self.kp.priv, digest)
        self._flip = not self._flip
        if self._flip and p256.is_low_s(s):
            s = p256.N - s  # produce the high-S form like a raw HSM
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


@pytest.fixture
def provider():
    return PKCS11Provider(FakeToken()), FakeToken


def test_token_signatures_match_software_contract():
    token = FakeToken()
    prov = PKCS11Provider(token)
    pub = ECDSAPublicKey(*token.kp.pub)
    for i in range(4):  # both high-S and low-S raw forms
        digest = prov.hash(b"msg-%d" % i)
        sig = prov.sign_by_ski(token.ski, digest)
        # the DER signature must verify through the SOFTWARE provider
        # (low-S enforced): token-signed and host-signed bytes are
        # indistinguishable to every verifier in the system
        assert SoftwareProvider().verify(pub, sig, digest)
        assert prov.verify(pub, sig, digest)
        _r, s = der.unmarshal_signature(sig)
        assert p256.is_low_s(s)


def test_handle_cache_and_unknown_ski():
    token = FakeToken()
    prov = PKCS11Provider(token)
    digest = prov.hash(b"x")
    prov.sign_by_ski(token.ski, digest)
    prov.sign_by_ski(token.ski, digest)
    assert token.find_calls == 1  # handle cached per SKI
    with pytest.raises(PKCS11Error):
        prov.sign_by_ski(b"\x00" * 20, digest)


def test_batch_verify_masks_failures():
    token = FakeToken()
    prov = PKCS11Provider(token)
    pub = ECDSAPublicKey(*token.kp.pub)
    digest = prov.hash(b"m")
    good = prov.sign_by_ski(token.ski, digest)
    out = prov.batch_verify(
        [pub, pub, pub],
        [good, b"\x30\x02\x01\x01", good],
        [digest, digest, prov.hash(b"other")],
    )
    assert out == [True, False, False]


def test_factory_pkcs11_errors_hard():
    with pytest.raises(FactoryError):
        provider_from_config({"Default": "PKCS11", "PKCS11": {}})
    with pytest.raises(PKCS11Error):
        provider_from_config(
            {
                "Default": "PKCS11",
                "PKCS11": {"Library": "/nonexistent/libsofthsm2.so"},
            }
        )


def test_signing_identity_routes_through_token(tmp_path):
    """HSM deployment: keystore-less MSP dir + PKCS11 provider ->
    SigningIdentity signs THROUGH the token session; the scalar never
    exists in process (msp/identities.go Sign via bccsp/pkcs11)."""
    import os

    from cryptography.hazmat.primitives import serialization

    from fabric_tpu.msp.configbuilder import load_signing_identity
    from fabric_tpu.msp.cryptogen import OrgCA

    token = FakeToken()
    prov = PKCS11Provider(token)

    # enroll a cert whose PUBLIC key is the token key, then write an
    # MSP dir with signcerts but NO keystore (the HSM layout)
    ca = OrgCA("hsm.test", "Org1MSP")
    ident = ca.enroll("peer0.hsm.test")
    # graft the token's public key into the SKI derivation by signing
    # over the real enrolled cert: the token addresses its key by the
    # cert-derived SKI, so point FakeToken at that SKI
    cert = __import__("cryptography").x509.load_pem_x509_certificate(
        ident.cert_pem
    )
    point = cert.public_key().public_bytes(
        serialization.Encoding.X962,
        serialization.PublicFormat.UncompressedPoint,
    )
    token.ski = hashlib.sha256(point).digest()
    # the fake token must sign with the key MATCHING the cert
    token.kp = type(token.kp)(
        priv=ident.key.private_numbers().private_value,
        pub=(
            cert.public_key().public_numbers().x,
            cert.public_key().public_numbers().y,
        ),
    )

    msp_dir = tmp_path / "msp"
    os.makedirs(msp_dir / "signcerts")
    (msp_dir / "signcerts" / "cert.pem").write_bytes(ident.cert_pem)

    signer = load_signing_identity(str(msp_dir), "Org1MSP", provider=prov)
    assert signer.node.key is None and signer.node.token_ski == token.ski
    sig = signer.sign(b"hello hsm")
    pub = ECDSAPublicKey(*token.kp.pub)
    assert SoftwareProvider().verify(pub, sig, prov.hash(b"hello hsm"))

    # without a PKCS11 provider, the keystore-less dir is still an error
    with pytest.raises(ValueError):
        load_signing_identity(str(msp_dir), "Org1MSP")
