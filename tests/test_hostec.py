"""Differential tests: the dependency-free vectorized host EC tier
(crypto/hostec) vs the pure-Python oracle (crypto/p256).

hostec is the middle tier of the host EC backend ladder (fastec ->
hostec -> p256) and the default host execution path wherever the
`cryptography` package is absent — these tests pin its valid/invalid
mask bit-exactly to the oracle across adversarial lanes (bit-flipped
signatures, high-S, boundary r/s, off-curve and identity keys) and
prove the process-pool sharding is order-preserving.

The oracle runs ~0.13s per verify, so oracle-compared lanes are kept to
a few dozen per test; large batches assert against constructed ground
truth (we signed them, we know the mask) and the full 1024-lane
differential rides the `slow` marker.
"""

import hashlib
import random

import pytest

from fabric_tpu.crypto import der, hostec, p256
from fabric_tpu.crypto.bccsp import (
    ECDSAPublicKey,
    SoftwareProvider,
    ec_backend_name,
    select_ec_backend,
)

N = p256.N
P = p256.P


def _digest(tag, i):
    return hashlib.sha256(b"%s %d" % (tag, i)).digest()


@pytest.fixture(scope="module")
def keypairs():
    return [hostec.generate_keypair() for _ in range(4)]


def _signed_lane(keypairs, tag, i):
    kp = keypairs[i % len(keypairs)]
    d = _digest(tag, i)
    r, s = hostec.sign_digest(kp.priv, d)
    return kp.pub, d, r, s


def _oracle_mask(lanes):
    return [p256.verify_digest(pub, d, r, s) for pub, d, r, s in lanes]


# ---------------------------------------------------------------------------
# Differential fuzz vs the oracle
# ---------------------------------------------------------------------------


def test_fuzz_mask_matches_oracle(keypairs):
    """Mixed batch: valid, bit-flipped r, bit-flipped s, wrong digest,
    high-S — one vectorized pass, bit-exact with the per-lane oracle."""
    rng = random.Random(0xEC)
    lanes = []
    for i in range(24):
        pub, d, r, s = _signed_lane(keypairs, b"fuzz", i)
        kind = i % 4
        if kind == 1:
            r ^= 1 << rng.randrange(256)
        elif kind == 2:
            s ^= 1 << rng.randrange(256)
        elif kind == 3:
            d = _digest(b"other", i)
        lanes.append((pub, d, r, s))
    assert hostec.verify_parsed_batch(lanes) == _oracle_mask(lanes)


def test_high_s_accepted_like_oracle(keypairs):
    """No low-S rule at this layer (Go crypto/ecdsa.Verify semantics):
    s and n-s are both valid. Callers gate low-S via parse_and_precheck."""
    lanes = []
    for i in range(4):
        pub, d, r, s = _signed_lane(keypairs, b"highs", i)
        lanes.append((pub, d, r, N - s))
    mask = hostec.verify_parsed_batch(lanes)
    assert mask == [True] * 4
    assert mask == _oracle_mask(lanes)


def test_rs_boundary_values(keypairs):
    """r/s in {0, 1, n-1, n, n+1}: out-of-range returns False without
    raising; in-range boundary values run the full math. Bit-exact with
    the oracle either way."""
    pub, d, r, s = _signed_lane(keypairs, b"edge", 0)
    edges = [0, 1, N - 1, N, N + 1]
    lanes = [(pub, d, e, s) for e in edges]
    lanes += [(pub, d, r, e) for e in edges]
    lanes.append((pub, d, r, s))  # control lane stays valid
    got = hostec.verify_parsed_batch(lanes)
    assert got == _oracle_mask(lanes)
    assert got[-1] is True
    assert not any(got[:-1])


def test_bad_public_keys(keypairs):
    """Off-curve, out-of-range and identity (None) keys verify False and
    never raise — even mixed into a batch with healthy lanes."""
    pub, d, r, s = _signed_lane(keypairs, b"badkey", 0)
    x, y = pub
    lanes = [
        ((x, (y + 1) % P), d, r, s),  # off curve
        ((P, y), d, r, s),  # x out of range
        ((x, P + y), d, r, s),  # y out of range
        (None, d, r, s),  # identity / unparseable
        (pub, d, r, s),  # healthy control
    ]
    got = hostec.verify_parsed_batch(lanes)
    assert got == [False, False, False, False, True]
    assert got == _oracle_mask(lanes)


def test_batch_sizes(keypairs):
    """Sizes around the window/shard seams: every 3rd lane corrupted;
    the mask must match the construction exactly at each size."""
    for size in (1, 2, 31, 32, 33):
        lanes = []
        expect = []
        for i in range(size):
            pub, d, r, s = _signed_lane(keypairs, b"size%d" % size, i)
            if i % 3 == 1:
                s ^= 2
                expect.append(False)
            else:
                expect.append(True)
            lanes.append((pub, d, r, s))
        assert hostec.verify_parsed_batch(lanes) == expect, size


def test_batch_1024_ground_truth(keypairs):
    """The acceptance-size batch (1024) against constructed truth; the
    per-lane oracle differential for this size is the slow variant."""
    lanes = []
    expect = []
    for i in range(1024):
        pub, d, r, s = _signed_lane(keypairs, b"kilo", i)
        if i % 5 == 2:
            r ^= 1 << (i % 250)
            expect.append(False)
        else:
            expect.append(True)
        lanes.append((pub, d, r, s))
    assert hostec.verify_parsed_batch_sharded(lanes)() == expect


@pytest.mark.slow
def test_batch_1024_differential_slow(keypairs):
    lanes = []
    for i in range(1024):
        pub, d, r, s = _signed_lane(keypairs, b"kiloslow", i)
        if i % 4 == 3:
            s ^= 1 << (i % 250)
        lanes.append((pub, d, r, s))
    assert hostec.verify_parsed_batch_sharded(lanes)() == _oracle_mask(lanes)


# ---------------------------------------------------------------------------
# Scalar API parity + sign/verify round trips
# ---------------------------------------------------------------------------


def test_sign_verify_cross_backend(keypairs):
    """hostec-signed verifies under the oracle and vice versa; low-S
    normalization matches the reference signer on both."""
    kp = keypairs[0]
    d = _digest(b"cross", 0)
    r, s = hostec.sign_digest(kp.priv, d)
    assert s <= p256.HALF_N
    assert p256.verify_digest(kp.pub, d, r, s)
    r2, s2 = p256.sign_digest(kp.priv, d)
    assert s2 <= p256.HALF_N
    assert hostec.verify_digest(kp.pub, d, r2, s2)


def test_scalar_base_mult_matches_oracle():
    for k in (1, 2, 15, 16, 0xDEADBEEF, N - 1, N, N + 7):
        assert hostec.scalar_base_mult(k) == p256.scalar_mult(
            k, p256.GENERATOR
        ), k


# ---------------------------------------------------------------------------
# Process-pool sharding
# ---------------------------------------------------------------------------


def test_sharded_is_order_preserving(keypairs, monkeypatch):
    """A pool-sized batch (>= MIN_POOL_LANES) sharded across 2 workers
    returns the same mask, in the same order, as the in-process pass."""
    monkeypatch.setenv("FABRIC_TPU_HOSTEC_PROCS", "2")
    hostec.shutdown_pool()  # force re-read of the env on next use
    lanes = []
    for i in range(hostec.MIN_POOL_LANES + 7):
        pub, d, r, s = _signed_lane(keypairs, b"shard", i)
        if i % 7 == 3:
            r ^= 4
        lanes.append((pub, d, r, s))
    try:
        sharded = hostec.verify_parsed_batch_sharded(lanes)()
    finally:
        hostec.shutdown_pool()
    assert sharded == hostec.verify_parsed_batch(lanes)


# ---------------------------------------------------------------------------
# Provider + VerifyBatcher integration (the validator's path)
# ---------------------------------------------------------------------------


@pytest.fixture()
def hostec_backend():
    """Pin the ladder to hostec for the duration, restoring after."""
    before = ec_backend_name()
    select_ec_backend("hostec")
    yield
    select_ec_backend(before)


def _provider_triples(keypairs, tag, n):
    keys, sigs, digests, expect = [], [], [], []
    for i in range(n):
        kp = keypairs[i % len(keypairs)]
        d = _digest(tag, i)
        r, s = hostec.sign_digest(kp.priv, d)
        if i % 3 == 2:
            d = _digest(tag + b"!", i)
            expect.append(False)
        else:
            expect.append(True)
        keys.append(ECDSAPublicKey(*kp.pub))
        sigs.append(der.marshal_signature(r, s))
        digests.append(d)
    return keys, sigs, digests, expect


def test_software_provider_batch_on_hostec(hostec_backend, keypairs):
    sw = SoftwareProvider()
    assert sw.describe_backend() == "sw:hostec"
    keys, sigs, digests, expect = _provider_triples(keypairs, b"prov", 12)
    # a DER-garbage lane and a high-S lane must fail the precheck and
    # come back False (not raise) on the batch path
    keys.append(keys[0])
    sigs.append(b"\x30\x03\x02\x01\x01")
    digests.append(digests[0])
    expect.append(False)
    assert sw.batch_verify(keys, sigs, digests) == expect


def test_auto_ladder_lands_on_host_tier_without_cryptography():
    """In an environment without the cryptography package, `auto` must
    select hostec_np (numpy present) or hostec — never the oracle —
    the silent-fallback cliff this ladder exists to remove."""
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography installed: auto selects fastec here")
    except ImportError:
        pass
    try:
        import numpy  # noqa: F401

        expect = "hostec_np"
    except ImportError:
        expect = "hostec"
    before = ec_backend_name()
    try:
        mod = select_ec_backend("auto")
        assert ec_backend_name() == expect
        if expect == "hostec":
            assert mod is hostec
        # an explicitly pinned fastec must raise, not downgrade
        with pytest.raises(ImportError):
            select_ec_backend("fastec")
    finally:
        select_ec_backend(before)


def test_verify_batcher_routes_through_hostec(hostec_backend, keypairs):
    """VerifyBatcher -> SoftwareProvider.batch_verify_async -> hostec
    sharded engine: per-request slices come back order-preserving even
    when requests coalesce into one sharded launch."""
    from fabric_tpu.parallel.batcher import VerifyBatcher

    calls = []
    orig = hostec.verify_parsed_batch_sharded

    def spy(lanes):
        calls.append(len(lanes))
        return orig(lanes)

    sw = SoftwareProvider()
    b = VerifyBatcher(sw, linger_s=0.02)
    try:
        hostec.verify_parsed_batch_sharded = spy
        reqs = [
            (_provider_triples(keypairs, b"vb%d" % i, 3 + i)) for i in range(4)
        ]
        resolvers = [b.submit(k, s, d) for k, s, d, _ in reqs]
        for resolver, (_k, _s, _d, expect) in zip(resolvers, reqs):
            assert resolver() == expect
    finally:
        hostec.verify_parsed_batch_sharded = orig
        b.stop()
    # every submitted lane went through the hostec engine
    assert sum(calls) == sum(3 + i for i in range(4))
